"""Live per-target search state (reference src/search.h).

This is the *protocol* half of the lookup engine: per-node write tokens,
get/listen/announce request tracking, α-throttling and the k=8 sync
rule, driven over the real network by :class:`~.dht.Dht`.  The *math*
half — which candidates are closest — comes from the TPU node table
(``core/table.py``); the batched offline simulator lives in
``core/search.py``.

Semantics mirror the reference exactly: a search keeps ≤ SEARCH_NODES
candidates sorted by XOR distance to the target (``Search::insertNode``,
src/search.h:636-722); it is *synced* when the first TARGET_NODES good
candidates hold fresh tokens (src/search.h:734-747); gets complete when
those nodes have answered (src/search.h:767-780); announces/listens are
sent only to synced nodes and refreshed before expiry
(src/search.h:325-347).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, TYPE_CHECKING

from ..infohash import InfoHash
from ..core.op_cache import SearchCache
from ..core.value import Filter, Query, Value
from ..core.value_cache import ValueCache
from ..net.node import NODE_EXPIRE_TIME, Node
from ..net.request import Request
from ..scheduler import Job, Scheduler
from ..utils import TIME_MAX

if TYPE_CHECKING:
    from ..core.value import TypeStore

_NEVER = float("-inf")

# protocol constants (reference dht.h:305-342)
SEARCH_NODES = 14                    # candidate set size (dht.h:308)
MAX_REQUESTED_SEARCH_NODES = 4       # α in-flight gets (dht.h:321)
LISTEN_NODES = 4                     # listen replication (dht.h:324)
TARGET_NODES = 8                     # k convergence/replication (routing_table.h:26)
SEARCH_MAX_BAD_NODES = 25            # ⇒ connectivity change (dht.h:310-318)
SEARCH_EXPIRE_TIME = 62 * 60.0       # idle search GC (dht.h:332)
LISTEN_EXPIRE_TIME = 30.0            # remote listener lifetime (dht.h:338)
REANNOUNCE_MARGIN = 10.0             # refresh this early (dht.h:340)


def acked_request(now: float) -> Request:
    """Synthetic completed request: marks a value as already announced
    without a wire round-trip (reference dht.cpp:573-577)."""
    from ..net.request import RequestState
    req = Request(None, 0, None, b"", None, None)
    req.state = RequestState.COMPLETED
    req.reply_time = now
    return req


def cancelled_request() -> Request:
    """Dummy request standing for 'this get is already satisfied'
    (reference dht.cpp:222-230)."""
    from ..net.request import RequestState
    req = Request(None, 0, None, b"", None, None)
    req.state = RequestState.CANCELLED
    return req


@dataclass
class Get:
    """One pending 'get' op attached to a search (src/search.h:32-39)."""
    start: float
    filter: Optional[Filter]
    query: Query
    query_cb: Optional[Callable] = None
    get_cb: Optional[Callable] = None
    done_cb: Optional[Callable] = None


@dataclass
class Announce:
    """One pending 'put' op (src/search.h:44-49)."""
    permanent: bool
    value: Value
    created: float
    callback: Optional[Callable] = None


@dataclass
class SearchListener:
    """(src/search.h:381-382 SearchListener)"""
    query: Query
    filter: Optional[Filter]
    get_cb: Callable


class CachedListenStatus:
    """Listen contract with one node: push-socket request + value cache
    (src/search.h:64-73)."""

    __slots__ = ("cache", "cache_expiration_job", "req")

    def __init__(self, cb):
        self.cache = ValueCache(cb)
        self.cache_expiration_job: Optional[Job] = None
        self.req: Optional[Request] = None


class SearchNode:
    """Per-(search, node) protocol state (src/search.h:51-355)."""

    __slots__ = ("node", "probe_query", "pagination_queries", "get_status",
                 "listen_status", "acked", "token", "last_get_reply",
                 "candidate", "sync_job", "depth")

    def __init__(self, node: Node):
        self.node = node
        # discovery generation within this search: 0 = seeded from the
        # local table/bootstrap, d+1 = learned from a depth-d node's
        # reply.  Drives the protocol-level hop metric (Search.
        # current_hops) validated against core/search.py's simulator.
        self.depth = 0
        self.probe_query: Optional[Query] = None
        # get query → sub-queries substituting it (pagination)
        self.pagination_queries: Dict[Query, List[Query]] = {}
        self.get_status: Dict[Query, Request] = {}
        self.listen_status: Dict[Query, CachedListenStatus] = {}
        # value id → (announce/refresh request, next refresh time)
        self.acked: Dict[int, tuple] = {}
        self.token = b""
        self.last_get_reply = _NEVER
        self.candidate = False
        self.sync_job: Optional[Job] = None

    # -- sync ---------------------------------------------------------------
    def is_synced(self, now: float) -> bool:
        """Fresh token ⇒ can listen/announce (src/search.h:112-115)."""
        return (not self.node.expired and bool(self.token)
                and self.last_get_reply >= now - NODE_EXPIRE_TIME)

    def get_sync_time(self, now: float) -> float:
        if self.node.expired or not self.token:
            return now
        return self.last_get_reply + NODE_EXPIRE_TIME

    def can_get(self, now: float, update: float, q: Optional[Query]) -> bool:
        """Whether a 'get'(q) should be sent to this node now
        (src/search.h:139-161)."""
        if self.node.expired:
            return False
        pending = False
        completed_sq = False
        pending_sq = False
        for sq, req in self.get_status.items():
            if req is not None and req.pending:
                pending = True
            if q is not None and req is not None and q.is_satisfied_by(sq):
                if req.pending:
                    pending_sq = True
                elif req.completed and not update > req.reply_time:
                    completed_sq = True
        return (not pending and now > self.last_get_reply + NODE_EXPIRE_TIME) or \
            not (completed_sq or pending_sq or self.has_started_pagination(q))

    def has_started_pagination(self, q: Optional[Query]) -> bool:
        """(src/search.h:169-180)"""
        pqs = self.pagination_queries.get(q)
        if not pqs:
            return False
        return any(pq in self.get_status for pq in pqs)

    def is_done(self, get: Get) -> bool:
        """Node finished answering this get (incl. all pagination
        sub-requests) (src/search.h:193-211)."""
        if self.has_started_pagination(get.query):
            return not any(
                (req := self.get_status.get(pq)) is not None and req.pending
                for pq in self.pagination_queries.get(get.query, ()))
        req = self.get_status.get(get.query)
        return req is not None and not req.pending

    def cancel_get(self) -> None:
        for req in self.get_status.values():
            if req.pending:
                self.node.cancel_request(req)
        self.get_status.clear()

    # -- listen -------------------------------------------------------------
    def on_values(self, q: Query, answer, types: "TypeStore",
                  scheduler: Scheduler) -> None:
        """Feed pushed/polled values into the per-query cache
        (src/search.h:216-226)."""
        ls = self.listen_status.get(q)
        if ls is not None:
            nxt = ls.cache.on_values(answer.values, answer.refreshed_values,
                                     answer.expired_values, types,
                                     scheduler.time())
            ls.cache_expiration_job = scheduler.edit(
                ls.cache_expiration_job, nxt)

    def expire_values(self, q: Query, scheduler: Scheduler) -> None:
        ls = self.listen_status.get(q)
        if ls is not None:
            nxt = ls.cache.expire_values(scheduler.time())
            ls.cache_expiration_job = scheduler.edit(
                ls.cache_expiration_job, nxt)

    def is_listening(self, now: float, q: Optional[Query] = None) -> bool:
        """(src/search.h:296-311)"""
        statuses = ([self.listen_status[q]] if q is not None
                    and q in self.listen_status
                    else ([] if q is not None
                          else list(self.listen_status.values())))
        return any(ls.req is not None
                   and ls.req.reply_time + LISTEN_EXPIRE_TIME > now
                   for ls in statuses)

    def cancel_listen(self, q: Optional[Query] = None) -> None:
        if q is None:
            for ls in self.listen_status.values():
                self.node.cancel_request(ls.req)
                if ls.cache_expiration_job:
                    ls.cache_expiration_job.cancel()
            self.listen_status.clear()
        else:
            ls = self.listen_status.pop(q, None)
            if ls is not None:
                self.node.cancel_request(ls.req)
                if ls.cache_expiration_job:
                    ls.cache_expiration_job.cancel()

    def get_listen_time(self, q: Query) -> float:
        """When the listen(q) contract must be refreshed
        (src/search.h:341-347)."""
        ls = self.listen_status.get(q)
        if ls is None or ls.req is None:
            return _NEVER
        if ls.req.pending:
            return TIME_MAX
        return ls.req.reply_time + LISTEN_EXPIRE_TIME - REANNOUNCE_MARGIN

    # -- announce -----------------------------------------------------------
    def is_announced(self, vid: int) -> bool:
        ack = self.acked.get(vid)
        return ack is not None and ack[0] is not None and ack[0].completed

    def cancel_announce(self) -> None:
        for req, _ in self.acked.values():
            if req is not None and req.pending:
                self.node.cancel_request(req)
        self.acked.clear()

    def get_announce_time(self, vid: int) -> float:
        """When a put(vid) should go out, assuming synced
        (src/search.h:325-337)."""
        ack = self.acked.get(vid)
        probe = (self.get_status.get(self.probe_query)
                 if self.probe_query is not None else None)
        ack_req = ack[0] if ack is not None else None
        if ack_req is None and (probe is None or not probe.pending):
            return _NEVER
        if (probe is not None and probe.pending) or ack_req is None \
                or ack_req.pending:
            return TIME_MAX
        return ack[1] - REANNOUNCE_MARGIN if ack_req.completed else _NEVER

    # -- health -------------------------------------------------------------
    def pending_get(self) -> bool:
        return any(r is not None and r.pending
                   for r in self.get_status.values())

    def is_bad(self) -> bool:
        """(src/search.h:350-352)"""
        return self.node is None or self.node.expired or self.candidate


class Search:
    """One target's candidate set + attached ops (src/search.h:361-630)."""

    def __init__(self, target: InfoHash, family: int, tid: int = 0,
                 clock: Optional[Callable[[], float]] = None):
        self.id = target
        self.af = family
        self.tid = tid
        self.refill_time = _NEVER
        #: a coalesced refill is riding the ingest wave builder (round
        #: 12): dedupes duplicate submissions and holds off the
        #: consecutive-bad-nodes expiry until the wave lands
        self.refill_pending = False
        self.step_time = _NEVER
        self.next_search_step: Optional[Job] = None
        # ISSUE-4: the trace context of the op that (re)started this
        # search — scheduler-driven steps re-activate it so every hop's
        # RPC parents under the originating get/put/listen span (a
        # reused search adopts the newest op's context, traced or not:
        # an untraced op must clear a predecessor's finished trace)
        self.trace_ctx = None
        self.expired = False
        self.done = False
        #: candidate-set bound (ISSUE-11): SEARCH_NODES normally; the
        #: announce path widens it for keys in the hot set so a
        #: closest-16 replica walk has candidates to walk (narrowed
        #: back on decay — Dht._search_send_announce re-evaluates it)
        self.capacity = SEARCH_NODES
        self.nodes: List[SearchNode] = []
        self.announce: List[Announce] = []
        self.callbacks: List[Get] = []           # kept in start-time order
        self.listeners: Dict[int, SearchListener] = {}
        self.listener_token = 1
        # clock keeps the op-dedup linger anchored to dispatch-time
        # removals (see OpCache._dispatch)
        self.cache = SearchCache(clock=clock)
        self.op_expiration_job: Optional[Job] = None

    # -- candidate set ------------------------------------------------------
    def insert_node(self, node: Node, now: float, token: bytes = b"",
                    depth: Optional[int] = None) -> bool:
        """Sorted insert by XOR distance to target, trimming to
        SEARCH_NODES live candidates (src/search.h:636-722).  Returns True
        if the node is new to this search.

        ``depth`` is the discovery generation (see SearchNode.depth):
        None leaves an existing node untouched (new nodes default to 0);
        a value applies min-rule so re-discovery through a shorter chain
        lowers the recorded depth."""
        if node.family != self.af:
            return False

        # find the node, or the sorted insertion point
        idx = len(self.nodes)
        found = False
        while idx > 0:
            sn = self.nodes[idx - 1]
            if sn.node is node:
                idx -= 1
                found = True
                break
            if self.id.xor_cmp(node.id, sn.node.id) > 0:
                break
            idx -= 1

        new_node = False
        if not found:
            cap = self.capacity
            bad = 0
            if self.expired:
                full = len(self.nodes) >= cap
                trim_at = cap if full else len(self.nodes)
            else:
                bad = self.get_number_of_bad_nodes()
                full = len(self.nodes) - bad >= cap
                trim_at = len(self.nodes)
                while trim_at - bad > cap:
                    trim_at -= 1
                    if self.nodes[trim_at].is_bad():
                        bad -= 1
            if full:
                if trim_at < len(self.nodes):
                    del self.nodes[trim_at:]
                if idx >= trim_at:
                    return False
            if not self.nodes:
                self.step_time = _NEVER
            sn_new = SearchNode(node)
            if depth is not None:
                sn_new.depth = depth
            self.nodes.insert(idx, sn_new)
            new_node = True
            if node.expired:
                if not self.expired:
                    bad += 1
            elif self.expired:
                bad = len(self.nodes) - 1
                self.expired = False
            while len(self.nodes) - bad > cap:
                if not self.expired and self.nodes[-1].is_bad():
                    bad -= 1
                self.nodes.pop()

        if found and depth is not None and depth < self.nodes[idx].depth:
            self.nodes[idx].depth = depth
        if token:
            sn = self.nodes[idx]
            sn.candidate = False
            sn.last_get_reply = now
            if len(token) <= 64:
                sn.token = token
            self.expired = False
        if new_node:
            self.remove_expired_node(now)
        return new_node

    def get_node(self, node: Node) -> Optional[SearchNode]:
        for sn in self.nodes:
            if sn.node is node:
                return sn
        return None

    def get_nodes(self) -> List[Node]:
        return [sn.node for sn in self.nodes]

    def current_hops(self, k: int = TARGET_NODES) -> Optional[int]:
        """Protocol-level hop count: the deepest discovery generation
        among the first k candidates that have replied, i.e. how many
        sequential reply rounds separated the final converged set from
        the seeds.  Comparable to core/search.py simulate_lookups'
        ``hops`` output (its round counter equals this depth metric:
        a node merged in round r carries generation r).  None until at
        least one candidate replied."""
        depths = [sn.depth for sn in self.nodes[:k]
                  if sn.last_get_reply > _NEVER]
        return max(depths) if depths else None

    def remove_expired_node(self, now: float) -> bool:
        """(src/search.h:539-551)"""
        for i in range(len(self.nodes) - 1, -1, -1):
            if self.nodes[i].node.is_removable(now):
                del self.nodes[i]
                return True
        return False

    # -- health -------------------------------------------------------------
    def get_number_of_bad_nodes(self) -> int:
        return sum(1 for sn in self.nodes if sn.is_bad())

    def get_number_of_consecutive_bad_nodes(self) -> int:
        count = 0
        for sn in self.nodes:
            if not sn.is_bad():
                break
            count += 1
        return count

    def currently_solicited_node_count(self) -> int:
        return sum(1 for sn in self.nodes
                   if not sn.is_bad() and sn.pending_get())

    # -- state predicates ---------------------------------------------------
    def is_synced(self, now: float) -> bool:
        """First k live candidates hold fresh tokens
        (src/search.h:734-747)."""
        i = 0
        for sn in self.nodes:
            if sn.is_bad():
                continue
            if not sn.is_synced(now):
                return False
            i += 1
            if i == TARGET_NODES:
                break
        return i > 0

    def is_done(self, get: Get) -> bool:
        """(src/search.h:767-780)"""
        i = 0
        for sn in self.nodes:
            if sn.is_bad():
                continue
            if not sn.is_done(get):
                return False
            i += 1
            if i == TARGET_NODES:
                break
        return True

    def is_announced(self, vid: int) -> bool:
        """(src/search.h:782-797)"""
        if not self.nodes:
            return False
        i = 0
        for sn in self.nodes:
            if sn.is_bad():
                continue
            if not sn.is_announced(vid):
                return False
            i += 1
            if i == TARGET_NODES:
                return True
        return i > 0

    def is_listening(self, now: float) -> bool:
        """(src/search.h:799-820)"""
        if not self.nodes or not self.listeners:
            return False
        i = 0
        for sn in self.nodes:
            if sn.is_bad():
                continue
            if not sn.is_listening(now):
                return False
            i += 1
            if i == LISTEN_NODES:
                break
        return i > 0

    def get_last_get_time(self, q: Optional[Query] = None) -> float:
        last = _NEVER
        for g in self.callbacks:
            if q is None or q.is_satisfied_by(g.query):
                last = max(last, g.start)
        return last

    # -- completion ---------------------------------------------------------
    def set_get_done(self, get: Get) -> None:
        """One get op is over: drop its per-node request state and fire the
        done callback (src/search.h:448-461)."""
        for sn in self.nodes:
            for pq in sn.pagination_queries.get(get.query, ()):
                sn.get_status.pop(pq, None)
            sn.get_status.pop(get.query, None)
        if get.done_cb:
            get.done_cb(True, self.get_nodes())

    def set_done(self) -> None:
        """(src/search.h:467-475)"""
        for sn in self.nodes:
            sn.get_status.clear()
            sn.listen_status.clear()
            sn.acked.clear()
        self.done = True

    def get_next_step_time(self, now: float) -> float:
        """Earliest *future* time this search needs a step: announce and
        listen refreshes on the nodes that carry them.  Drives the
        step job's self-rescheduling so permanent puts and listens are
        refreshed before their remote expiry even on an otherwise idle
        node (the reference leaves this to ambient traffic —
        src/dht.cpp:651-653 commented out — which strands refreshes on
        quiet networks; newer upstream adds the same scheduling)."""
        if self.expired or self.done or not self.is_synced(now):
            return TIME_MAX
        nxt = TIME_MAX
        if self.announce:
            i = 0
            for sn in self.nodes:
                if sn.is_bad():
                    continue
                for a in self.announce:
                    t = sn.get_announce_time(a.value.id)
                    if now < t < nxt:
                        nxt = t
                if not sn.candidate:
                    i += 1
                    if i == TARGET_NODES:
                        break
        if self.listeners:
            i = 0
            for sn in self.nodes:
                if sn.is_bad():
                    continue
                for q in list(sn.listen_status):
                    t = sn.get_listen_time(q)
                    if now < t < nxt:
                        nxt = t
                if not sn.candidate:
                    i += 1
                    if i == LISTEN_NODES:
                        break
        return nxt

    def check_announced(self, vid: int = Value.INVALID_ID) -> None:
        """Fire callbacks of fully-announced values; drop non-permanent
        ones (src/search.h:592-619)."""
        kept: List[Announce] = []
        cleared_vids: List[int] = []
        for a in self.announce:
            if vid != Value.INVALID_ID and (a.value is None
                                            or a.value.id != vid):
                kept.append(a)
                continue
            if self.is_announced(a.value.id):
                if a.callback:
                    a.callback(True, self.get_nodes())
                    a.callback = None
                if not a.permanent:
                    cleared_vids.append(a.value.id)
                    continue
            kept.append(a)
        for cleared in cleared_vids:
            for sn in self.nodes:
                sn.acked.pop(cleared, None)
        self.announce = kept

    def expire(self) -> None:
        """All nodes gone/expired — likely connectivity change
        (src/search.h:557-590)."""
        self.expired = True
        self.nodes.clear()
        if not self.announce and not self.listeners:
            self.set_done()
        get_cbs, self.callbacks = self.callbacks, []
        for g in get_cbs:
            if g.done_cb:
                g.done_cb(False, [])
        a_cbs = []
        kept = []
        for a in self.announce:
            if a.callback:
                a_cbs.append(a.callback)
                a.callback = None
            if a.permanent:
                kept.append(a)
        self.announce = kept
        for cb in a_cbs:
            cb(False, [])

    def clear(self) -> None:
        self.announce.clear()
        self.callbacks.clear()
        self.listeners.clear()
        self.nodes.clear()
        if self.next_search_step:
            self.next_search_step.cancel()
            self.next_search_step = None

    def stop(self) -> None:
        """Destructor semantics (src/search.h:388-399)."""
        if self.op_expiration_job:
            self.op_expiration_job.cancel()
        for get in self.callbacks:
            if get.done_cb:
                get.done_cb(False, [])
                get.done_cb = None
        for put in self.announce:
            if put.callback:
                put.callback(False, [])
                put.callback = None
        for sn in self.nodes:
            sn.cancel_get()
            sn.cancel_listen()
            sn.cancel_announce()

    # -- listen attach ------------------------------------------------------
    def add_listener(self, get_cb, f: Optional[Filter], q: Query,
                     scheduler: Scheduler,
                     on_new: Callable[[], None]) -> int:
        """Register through the dedup cache (src/search.h:479-488)."""
        def attach(query: Query, vcb) -> int:
            self.done = False
            self.listener_token += 1
            token = self.listener_token
            self.listeners[token] = SearchListener(query, f, vcb)
            on_new()
            return token
        return self.cache.listen(get_cb, q, f, attach)

    def cancel_listen_token(self, token: int, scheduler: Scheduler) -> None:
        """(src/search.h:488-512)"""
        self.cache.cancel_listen(token, scheduler.time())

        def expire_ops():
            def on_cancel(t: int):
                sl = self.listeners.pop(t, None)
                for sn in self.nodes:
                    if not self.listeners:
                        sn.cancel_listen()
                    elif sl is not None:
                        sn.cancel_listen(sl.query)
            next_expire = self.cache.expire(scheduler.time(), on_cancel)
            self.op_expiration_job = scheduler.edit(
                self.op_expiration_job, next_expire)

        if self.op_expiration_job is None or self.op_expiration_job.cancelled:
            self.op_expiration_job = scheduler.add(TIME_MAX, expire_ops)
            # re-point the job body at itself for rescheduling
            self.op_expiration_job.func = expire_ops
        self.op_expiration_job = scheduler.edit(
            self.op_expiration_job, self.cache.get_expiration())
