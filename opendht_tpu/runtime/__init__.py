"""L4 — the node runtime: the full DHT node core (``Dht``), its live
search machinery, and the async runner façade.

The architectural split (SURVEY.md §7): per-packet protocol state — the
msgpack RPC engine, request retries, per-search token/listen/announce
bookkeeping — stays host-side where latency-bound scalar work belongs;
*all* closest-node math goes through the TPU-backed
:class:`~opendht_tpu.core.table.NodeTable` device snapshots, so a node
serving thousands of concurrent lookups resolves them in a handful of
batched XOR top-k device calls instead of per-search scalar scans
(reference: ``RoutingTable::findClosestNodes``
src/routing_table.cpp:109-150, ``NodeCache::getCachedNodes``
src/node_cache.cpp:41-74)."""

from .config import Config, NodeStatus, NodeStats, DEFAULT_STORAGE_LIMIT  # noqa: F401
from .dht import Dht  # noqa: F401
from .wave_builder import WaveBuilder  # noqa: F401
