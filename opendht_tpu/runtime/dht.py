"""The DHT node core (reference src/dht.cpp, include/opendht/dht.h).

Single-threaded and scheduler-driven like the reference: every behavior
is either a reaction to an incoming packet (``periodic``) or a scheduled
job.  Public ops (`get/put/listen/query`) attach work to per-target
:class:`~.live_search.Search` state machines; incoming RPCs are served
from the local value store and the routing table.

TPU-first redesign of the routing core: instead of scalar k-bucket
scans, both address families keep a :class:`~opendht_tpu.core.table.NodeTable`
— a numpy-backed peer slab whose closest-node queries run as batched XOR
top-k kernels on device snapshots (``find_closest_nodes`` accepts *many*
targets in one call, serving search refills, find-node replies and
announce distance checks from the same compiled kernel).  The per-packet
protocol state stays host-side where the reference keeps it; see
SURVEY.md §7's design mapping.
"""

from __future__ import annotations

import hashlib
import logging
import os
import random
import socket as _socket
from bisect import bisect_left, insort
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import telemetry, tracing
from ..infohash import InfoHash
from ..ops import ids as IK
from ..sockaddr import SockAddr
from ..scheduler import Scheduler
from ..utils import TIME_MAX, WANT4, WANT6, wall_now
from ..core.storage import Storage, StorageBucket
from ..core.listener import Listener, LocalListener
from ..core.op_cache import OpValueCache
from ..core.table import NodeTable
from ..core.value import (
    Field, FieldValueIndex, Filter, Filters, Query, Select, TypeStore, Value,
    Where, random_value_id,
)
from ..net.engine import (
    DhtProtocolException, EngineCallbacks, NetworkEngine, RequestAnswer,
)
from ..net.node import NODE_EXPIRE_TIME, MAX_RESPONSE_TIME, Node
from ..net.request import Request
from .config import Config, NodeStats, NodeStatus
from .live_search import (
    Announce, Get, LISTEN_NODES, MAX_REQUESTED_SEARCH_NODES, REANNOUNCE_MARGIN,
    SEARCH_EXPIRE_TIME, SEARCH_MAX_BAD_NODES, SEARCH_NODES, Search, SearchNode,
    TARGET_NODES, acked_request, cancelled_request,
)
from .wave_builder import WaveBuilder

log = logging.getLogger("opendht_tpu.dht")

_NEVER = float("-inf")

# (reference dht.h:305-357)
MAX_HASHES = 16384                   # stored keys cap (dht.h:327)
MAX_SEARCHES = 16384                 # concurrent searches cap (dht.h:330)
TOKEN_SIZE = 32                      # sha256 digest length (dht.h:342)
MAX_STORAGE_MAINTENANCE_EXPIRE_TIME = 10 * 60.0    # (dht.h:335)

#: storage-calendar quantum (round 10): per-key expiry/republish jobs
#: are binned to this many seconds and every bin shares ONE scheduler
#: heap entry, so K stored keys cost O(bins in flight) entries, not K.
#: Bins round UP, so no sweep ever fires before a key is due; the ≤10 s
#: lateness is noise against the 10-min expiry/republish horizons.
STORAGE_CALENDAR_QUANTUM = 10.0

#: the query standing for a token-only sync probe ('find_node' path)
_ANY_QUERY = Query(none=True)


def _traced_search(fn):
    """Re-activate the search's trace context around an RPC-sending
    method (ISSUE-4).  Search steps fire from scheduler jobs and reply
    callbacks, where the originating op's ambient context is long gone
    — the Search object carries it (live_search.Search.trace_ctx) and
    this wrapper restores it (including to None: a step of an untraced
    search must not inherit a foreign op's context), so the engine's
    ``send_*`` sites see the right parent for every hop."""
    def wrapper(self, sr, *args, **kw):
        with tracing.activate(sr.trace_ctx):
            return fn(self, sr, *args, **kw)
    wrapper.__name__ = fn.__name__
    wrapper.__doc__ = fn.__doc__
    return wrapper


def _quota_key(addr: SockAddr) -> tuple:
    """Per-IP quota bucket key (the reference keys StorageBucket by
    SockAddr with port zeroed, dht.h:374)."""
    return (addr.family, addr.ip.packed if addr.ip else b"")


class BatchedResolve:
    """Handle for an in-flight batched closest-NODE resolve (round-20
    wave pipeline) — the Node-materializing layer over
    core/table.PendingLookup.  ``ready()`` probes without blocking;
    ``consume()`` blocks on the device result and builds the
    ``List[List[Node]]`` the synchronous entry point returns (it is
    idempotent: ``find_closest_nodes_batched = launch().consume()``).
    ``shard_t`` is the shard width of THIS launch, captured because the
    shared ``Dht.last_resolve_shard_t`` may belong to a newer
    overlapping wave by the time this one is consumed."""

    __slots__ = ("shard_t", "_pending", "_finalize", "_done", "_result")

    def __init__(self, finalize, pending=None, shard_t: int = 1):
        self._finalize = finalize
        self._pending = pending           # core PendingLookup or None
        self.shard_t = int(shard_t or 1)
        self._done = False
        self._result = None

    @classmethod
    def resolved(cls, result, shard_t: int = 1) -> "BatchedResolve":
        br = cls(None, shard_t=shard_t)
        br._done = True
        br._result = result
        return br

    def ready(self) -> bool:
        return self._done or self._pending is None or self._pending.ready()

    def consume(self) -> List[List[Node]]:
        if not self._done:
            self._result = self._finalize()
            self._done = True
            self._finalize = None
            self._pending = None
        return self._result


class Dht:
    """A complete DHT node behind an injected datagram transport.

    ``send_fn(data, addr) -> errno`` is the only way bytes leave;
    ``periodic(data, from_addr)`` is the only way bytes enter — exactly
    the reference's socket-fd boundary (dht.h:62-116), kept callable so
    the same core runs over asyncio UDP, the C++ datagram engine, or an
    in-process virtual network in tests.
    """

    def __init__(self, send_fn: Callable[[bytes, SockAddr], int],
                 config: Optional[Config] = None,
                 scheduler: Optional[Scheduler] = None,
                 *, has_v4: bool = True, has_v6: bool = True):
        config = config or Config()
        self.config = config
        self.myid = config.node_id or InfoHash.get_random()
        self.is_bootstrap = config.is_bootstrap
        self.maintain_storage = config.maintain_storage
        # NB: an idle Scheduler is falsy (__len__ == 0) — test identity
        self.scheduler = scheduler if scheduler is not None else Scheduler()
        self.types = TypeStore()
        self._has = {_socket.AF_INET: has_v4, _socket.AF_INET6: has_v6}

        self.engine = NetworkEngine(
            self.myid, config.network, send_fn, self.scheduler,
            EngineCallbacks(
                on_error=self._on_error,
                on_new_node=self._on_new_node,
                on_reported_addr=self._on_reported_addr,
                on_ping=self._on_ping,
                on_find_node=self._on_find_node,
                on_get_values=self._on_get_values,
                on_listen=self._on_listen,
                on_announce=self._on_announce,
                on_refresh=self._on_refresh,
            ),
            is_client=config.is_bootstrap,
            max_req_per_sec=config.max_req_per_sec)

        # TPU-backed routing tables, one per family (↔ buckets4/6,
        # dht.h:370-381)
        self.tables: Dict[int, NodeTable] = {
            af: NodeTable(self.myid) for af, on in self._has.items() if on}
        self.searches: Dict[int, Dict[InfoHash, Search]] = {
            af: {} for af in self.tables}
        # sorted key lists for trySearchInsert's bidirectional walk
        self._search_keys: Dict[int, List[bytes]] = {af: [] for af in self.tables}
        self._search_id = random.randint(1, 0xFFFF)
        #: (key, vid) → live local-refresh Job for permanent puts
        self._local_refresh_jobs: Dict[tuple, object] = {}

        # value store (↔ dht.h:372-377)
        self.store: Dict[InfoHash, Storage] = {}
        self.store_quota: Dict[tuple, StorageBucket] = {}
        self.total_store_size = 0
        self.total_values = 0
        self.max_store_size = config.storage_limit
        self.max_store_keys = MAX_HASHES

        # global listener registry: token → (local, v4, v6) sub-tokens
        self.listeners: Dict[int, Tuple[int, int, int]] = {}
        self._listener_token = 0

        self.reported_addr: List[Tuple[int, SockAddr]] = []
        self._pending_pings = {af: 0 for af in self.tables}
        self._table_grow_time = {af: _NEVER for af in self.tables}
        self.status_cb: Optional[Callable[[NodeStatus, NodeStatus], None]] = None
        self._last_status = {af: NodeStatus.DISCONNECTED for af in self.tables}
        self._status_checked: Dict[int, float] = {}
        self._status_recheck: Dict[int, object] = {}

        # storage calendar (round 10): bin id -> keys due at that bin;
        # one scheduler job per OCCUPIED bin replaces the per-key
        # _data_persistence/_expire_storage jobs (see _calendar_add)
        self._storage_calendar: Dict[int, set] = {}

        # continuous-batching ingest (round 12): live search refills
        # from EVERY traffic source coalesce into shared [Q] device
        # launches; new ops shed at admission under backpressure
        # (wave_builder.py; config.ingest_* knobs)
        self.wave_builder = WaveBuilder(self, config)

        # keyspace traffic observatory (round 15, ISSUE-10): device
        # count-min sketch + top-8-bit histogram over the wave target
        # ids (one batched scatter-add per ingest wave, fed by the
        # wave builder) and stored-key puts; heavy-hitter top-K +
        # shard load-balance attribution tick on this scheduler
        from ..keyspace import KeyspaceObservatory
        self.keyspace = KeyspaceObservatory(
            getattr(config, "keyspace", None), node=str(self.myid),
            shard_info=self._keyspace_shard_info)
        self.keyspace.attach(self.scheduler)

        # hot-key serving cache (round 16, ISSUE-11): the acting half
        # of the observe→act loop — subscribes to the observatory tick,
        # keeps a bounded device table of the hot keys' ids (probed in
        # one batched XOR-compare launch before every ingest wave) +
        # host value payloads, and answers the adaptive replica-k
        # question for the announce/republish paths (hotcache.py;
        # config.cache knobs)
        from ..hotcache import HotValueCache
        self.hotcache = HotValueCache(
            getattr(config, "cache", None), node=str(self.myid),
            local_values=lambda kb: self.get_local(InfoHash(kb)),
            clock=self.scheduler.time)
        self.keyspace.subscribe(self.hotcache.on_keyspace_tick)

        # load-aware resharding (round 21, ISSUE-17): the rebalance
        # tick closing the loop on the observatory's imbalance gauge —
        # sustained windowed imbalance above threshold solves new
        # traffic-weighted shard boundaries and hot-swaps them under
        # the serving path between waves (reshard.py; config.reshard
        # knobs).  The runner late-binds the history ring for windowed
        # frame corroboration (set_history).
        from ..reshard import Resharder
        self.reshard = Resharder(
            getattr(config, "reshard", None), node=str(self.myid),
            keyspace=self.keyspace, shard_t=self.resolve_mesh_t,
            on_swap=self._reshard_apply, clock=self.scheduler.time)
        self.reshard.attach(self.scheduler)

        # per-peer network observatory (round 23, ISSUE-19): bounded
        # LRU ledger over remote peers — Jacobson/Karels RTT estimator,
        # per-peer request/byte/flap attribution, and (behind
        # config.peers.adaptive_rto) the per-peer retransmit timeout
        # the engine consults instead of the fixed MAX_RESPONSE_TIME
        # (peers.py; config.peers knobs).  Attached to the engine's
        # request lifecycle seams; a disabled ledger detaches entirely
        # (engine.peers = None, the pre-round-23 fast path).
        from ..peers import PeerLedger
        self.peers = PeerLedger(
            getattr(config, "peers", None), node=str(self.myid),
            clock=self.scheduler.time)
        self.engine.peers = self.peers if self.peers.enabled else None

        # wave-scale listen/push (round 24, ISSUE-20): a bounded
        # device table of the keys that currently have listeners —
        # every stored put buffers here instead of probing listener
        # dicts synchronously, and the next ingest wave (or the flush
        # deadline) answers membership for the whole buffer in ONE
        # batched XOR-equality launch (ops/listener_match.py), after
        # which flush_listener_wave dispatches one coalesced callback/
        # tell_listener per wave per listener.  listen_batching="off"
        # is the escape hatch (the exact synchronous per-put path);
        # device failure goes dark to the same path (listeners.py;
        # config.listeners knobs).
        from ..listeners import ListenerTable
        self.listener_table = ListenerTable(
            getattr(config, "listeners", None), node=str(self.myid),
            batching=getattr(config, "listen_batching", "on"),
            live_count=self._listener_live_count,
            clock=self.scheduler.time,
            request_flush=self._arm_listener_flush)
        self._listener_flush_job = None

        # per-op latency waterfall (round 19, ISSUE-15): the always-on
        # stage profiler every serving layer feeds (wave builder,
        # search envelope, net engine/request) — process-global like
        # the registry; this node's config wins, same last-node-wins
        # aggregation rule (waterfall.py; config.waterfall knobs)
        from .. import waterfall as _waterfall
        self.waterfall = _waterfall.get_profiler()
        self.waterfall.configure(
            getattr(config, "waterfall", None)
            or _waterfall.WaterfallConfig())

        # t-sharded resolve (round 13): lazily-built (q=1, t) mesh from
        # config.resolve_mesh_t; None until first use, False = probed
        # and unavailable (fewer devices than requested / no jax).
        # last_resolve_shard_t records what the MOST RECENT batched
        # resolve actually used (1 when the host scan / churn view
        # served it regardless of config).
        self._resolve_mesh = None
        self.last_resolve_shard_t = 1

        # maintenance telemetry (ISSUE-5): handles cached once
        _reg = telemetry.get_registry()
        self._m_maint_sweeps = _reg.counter("dht_maintenance_sweeps_total")
        self._m_maint_refresh = _reg.counter(
            "dht_maintenance_refresh_sent_total")
        self._m_maint_due = _reg.counter("dht_maintenance_due_keys_total")
        self._m_maint_republished = _reg.counter(
            "dht_maintenance_republished_values_total")
        self._m_calendar_bins = _reg.gauge("dht_maintenance_calendar_bins")

        # write-token secrets, rotated every 15-45 min (dht.cpp:1369-1379)
        self._secret = os.urandom(8)
        self._oldsecret = self._secret
        self._rotate_secrets()

        now = self.scheduler.time()
        self._next_nodes_confirmation = self.scheduler.add(
            now + random.uniform(3, 5), self._confirm_nodes)
        self._expire_sweep()

    # ================================================================ plumbing
    def _table(self, af: int) -> Optional[NodeTable]:
        return self.tables.get(af)

    def is_running(self, af: int = 0) -> bool:
        if af == 0:
            return bool(self.tables)
        return af in self.tables

    def _want(self) -> int:
        w = 0
        if _socket.AF_INET in self.tables:
            w |= WANT4
        if _socket.AF_INET6 in self.tables:
            w |= WANT6
        return w

    def periodic(self, data: Optional[bytes], from_addr: Optional[SockAddr]
                 ) -> float:
        """Feed one received datagram (or None) and run due jobs; returns
        the next wakeup time (↔ Dht::periodic, src/dht.cpp:1902-1914)."""
        self.scheduler.sync_time()
        if data:
            try:
                self.engine.process_message(data, from_addr)
            except Exception:
                log.exception("can't process message from %r", from_addr)
        return self.scheduler.run()

    def warmup(self) -> None:
        """Trigger the XLA compiles of the hot table kernels (snapshot
        sort, windowed top-k) so the first real packet doesn't stall the
        protocol thread behind a multi-second first-compile.  The top-k
        kernel is specialized per static ``k``, so warm every k the live
        path uses.  Compiled executables are cached per-process."""
        now = self.scheduler.time()
        target = [InfoHash.get_random()]
        for table in self.tables.values():
            try:
                for k in (TARGET_NODES, SEARCH_NODES):
                    table.find_closest(target, k=k, now=now)
            except Exception:
                log.debug("kernel warmup failed", exc_info=True)

    # ======================================================== routing plumbing
    def find_closest_nodes(self, target: InfoHash, af: int,
                           count: int = TARGET_NODES) -> List[Node]:
        """k closest good/reachable peers as engine Node objects
        (↔ RoutingTable::findClosestNodes, src/routing_table.cpp:109-150;
        one row of the batched device kernel)."""
        return self.find_closest_nodes_batched([target], af, count)[0]

    def resolve_mesh(self):
        """The (q=1, t) device mesh batched resolves row-shard over
        when ``config.resolve_mesh_t >= 2`` (round 13) — built once,
        ``None`` when unconfigured or when the host has fewer devices
        than requested (logged; serving degrades to the identical
        unsharded path, never fails)."""
        t = int(getattr(self.config, "resolve_mesh_t", 0) or 0)
        if t <= 1:
            return None
        if self._resolve_mesh is None:
            try:
                import jax
                from ..parallel import make_mesh
                if len(jax.devices()) < t:
                    log.warning(
                        "resolve_mesh_t=%d but only %d jax device(s); "
                        "serving the unsharded resolve path",
                        t, len(jax.devices()))
                    self._resolve_mesh = False
                else:
                    self._resolve_mesh = make_mesh(t, q=1, t=t)
            except Exception:
                log.exception("resolve mesh unavailable; serving unsharded")
                self._resolve_mesh = False
        return self._resolve_mesh or None

    def resolve_mesh_t(self) -> int:
        """Active resolve-shard width (1 = unsharded) — the ingest wave
        builder stamps this on its wave spans/snapshot."""
        m = self.resolve_mesh()
        return int(m.shape["t"]) if m is not None else 1

    def _reshard_apply(self, layout) -> dict:
        """Resharder swap hook, called inside the swap span with the
        NEW layout before it is installed: when a mesh and a snapshot
        are live, eagerly rebuild the snapshot's weighted shard state
        (row movement + placement + per-shard perm map,
        core/table.py ``Snapshot._shard_state``) so the next wave
        doesn't pay the rebuild — the swap wall-clock histogram then
        measures the real state-rebuild cost.  Runs on the DHT loop
        (scheduler job), i.e. strictly between wave launches; waves
        already in flight captured the OLD operands at launch."""
        mesh = self.resolve_mesh()
        if mesh is None:
            return {"mode": "virtual"}
        table = self._table(_socket.AF_INET)
        snap = getattr(table, "_snap", None) if table is not None else None
        if snap is None or int(snap.n_valid) < layout.t:
            return {"mode": "virtual"}
        snap._shard_state(mesh, layout)
        return {"mode": "physical", "t": int(mesh.shape["t"])}

    def _keyspace_shard_info(self):
        """(t, bounds[, virtual]) for the keyspace observatory's
        per-shard load attribution (ISSUE-10): when a resolve mesh is
        live, the ACTUAL first-row ids of shards 1..t-1 of the current
        v4 table snapshot (the row-sharded resolve splits the
        snapshot's cap rows contiguously, core/table.py
        Snapshot._lookup_sharded) — folding the traffic histogram over
        these is the real per-shard load.  ``(0, None)`` when unsharded
        (the observatory falls back to a uniform virtual split).

        With a reshard layout installed (ISSUE-17) the boundaries are
        re-read from the CURRENT snapshot at the layout's solved split
        — after a swap (or a snapshot rebuild) the fold attribution
        follows the new edges immediately; frames recorded before the
        swap keep the loads folded at their own tick.  Unsharded nodes
        return the layout's fractional edges with ``virtual=True`` so
        the virtual fold follows the resharded ownership too."""
        lay = getattr(self, "reshard", None)
        lay = lay.layout if lay is not None else None
        t = self.resolve_mesh_t()
        if t <= 1:
            if lay is not None and lay.t > 1:
                return lay.t, [float(e) for e in lay.edges], True
            return 0, None
        table = self._table(_socket.AF_INET)
        snap = getattr(table, "_snap", None) if table is not None else None
        if snap is None:
            return t, None
        if lay is not None:
            n_valid = int(snap.n_valid)
            if n_valid >= t:
                rows = np.asarray(
                    snap.reshard_boundary_rows(lay, t), np.int64)
                rows = np.clip(rows, 0, max(n_valid - 1, 0))
                return t, np.asarray(snap.sorted_ids[rows]), False
        cap = snap.sorted_ids.shape[0]
        # mirror the actual split: _shard_state pads cap UP to a
        # multiple of t before slicing, so the per-shard row count is
        # the ceiling — floor division would put every boundary one
        # partial-shard early on a ragged cap (review finding)
        shard_n = -(-cap // t)
        if shard_n == 0:
            return t, None
        n_valid = int(snap.n_valid)
        if n_valid <= (t - 1) * shard_n:
            # partially-filled table: at least one boundary row s*shard_n
            # falls past the valid rows and would clamp to the last valid
            # id — a zero-width trailing shard that reports fill-level
            # concentration as traffic imbalance (uniform traffic on a
            # 30%-full cap reads ~cap/n_valid, enough to trip the health
            # degrade threshold on a perfectly healthy node — review
            # finding; the fully-degenerate n_valid <= shard_n case is
            # the same hazard at imbalance t).  The signal exists to
            # detect TRAFFIC skew, so fall back to the uniform t-way
            # ring split whenever any boundary would clamp.
            return t, None
        rows = [s * shard_n for s in range(1, t)]
        return t, np.asarray(snap.sorted_ids[np.asarray(rows)])

    def find_closest_nodes_batched(self, targets: List[InfoHash], af: int,
                                   count: int = TARGET_NODES
                                   ) -> List[List[Node]]:
        """Batched form: resolve *many* targets with one device top-k
        call — the core TPU win for nodes serving thousands of concurrent
        requests (SURVEY.md §7 design mapping).  With a configured
        resolve mesh the device call is the t-sharded per-shard top-k +
        one cross-shard merge (core/table.py Snapshot.lookup)."""
        return self.find_closest_nodes_launch(targets, af, count).consume()

    def find_closest_nodes_launch(self, targets: List[InfoHash], af: int,
                                  count: int = TARGET_NODES
                                  ) -> BatchedResolve:
        """Async form of :meth:`find_closest_nodes_batched` (round-20
        wave pipeline): the device top-k is dispatched before this
        returns; the handle's ``consume()`` blocks on the device and
        materializes the Node lists.  ``handle.shard_t`` carries the
        per-launch shard width — overlapping waves must not read the
        shared ``last_resolve_shard_t`` at consume time."""
        # reset BEFORE any early return: a wave served by an empty
        # table (or one whose launch raises) must not inherit the
        # previous resolve's shard width (review finding)
        self.last_resolve_shard_t = 1
        table = self._table(af)
        if table is None or len(table) == 0 or not targets:
            return BatchedResolve.resolved([[] for _ in targets])
        now = self.scheduler.time()
        rs = getattr(self, "reshard", None)
        pl = table.find_closest_launch(
            list(targets), k=count, now=now, mesh=self.resolve_mesh(),
            layout=rs.layout if rs is not None else None)
        # truth, not config: the table says whether THIS resolve ran
        # sharded (host scans and churn views ignore the mesh) — the
        # ingest wave spans/counters attribute from this flag
        shard_t = (self.resolve_mesh_t()
                   if getattr(table, "last_resolve_sharded", False) else 1)
        self.last_resolve_shard_t = shard_t

        def finalize():
            rows, _dist = pl.consume()
            # one vectorized id conversion for the whole result matrix —
            # the per-row numpy round-trip dominated big batches
            # (table.py ids_of_rows)
            ids_flat = table.ids_of_rows(rows)
            out: List[List[Node]] = []
            k_out = rows.shape[1]
            for qi in range(rows.shape[0]):
                nodes: List[Node] = []
                for j in range(k_out):
                    r = rows[qi, j]
                    if r < 0:
                        continue
                    addr = table.addr_of(int(r))
                    if addr is None:
                        continue
                    nodes.append(self.engine.cache.get_node(
                        ids_flat[qi * k_out + j], addr, now, confirm=False))
                out.append(nodes)
            return out

        return BatchedResolve(finalize, pending=pl, shard_t=shard_t)

    def _searches_of(self, af: int) -> Dict[InfoHash, Search]:
        return self.searches.get(af, {})

    def get_search_hops(self, key: InfoHash,
                        af: int = _socket.AF_INET) -> Optional[int]:
        """Protocol-level hops-to-converge of the search on ``key``: the
        deepest discovery generation among the replied top-k candidates
        (live_search.Search.current_hops).  Validated against the batched
        simulator's hop counter in tests/test_hop_parity.py."""
        sr = self._searches_of(af).get(key)
        return sr.current_hops() if sr is not None else None

    def _try_search_insert(self, node: Node) -> bool:
        """Offer a newly-heard node to searches near its id, walking
        outward from its sorted position until a live search declines
        (↔ Dht::trySearchInsert, src/dht.cpp:118-150)."""
        now = self.scheduler.time()
        srs = self._searches_of(node.family)
        keys = self._search_keys.get(node.family)
        if not srs or keys is None:
            return False
        # when this node arrived inside a reply, attribute its discovery
        # generation per search: one deeper than the replying node's
        # (hop accounting — live_search.SearchNode.depth)
        via = self.engine.reply_via
        inserted = False
        pos = bisect_left(keys, bytes(node.id))
        for rng in (range(pos, len(keys)), range(pos - 1, -1, -1)):
            for i in rng:
                sr = srs[InfoHash(keys[i])]
                depth = None
                if via is not None:
                    vsn = sr.get_node(via)
                    depth = (vsn.depth + 1) if vsn is not None else 1
                if sr.insert_node(node, now, depth=depth):
                    inserted = True
                    self._edit_step(sr, now)
                elif not sr.expired and not sr.done:
                    break
        return inserted

    def _on_new_node(self, node: Node, confirm: int) -> None:
        """(↔ Dht::onNewNode, src/dht.cpp:166-172)"""
        table = self._table(node.family)
        if table is None:
            return
        was_known = table.row_of(node.id) is not None
        row = table.insert(node.id, node.addr, self.scheduler.time(),
                           confirm=confirm)
        if row is not None and confirm == 0 \
                and table._time_reply[row] == 0.0:
            # genuinely new hearsay node admitted into the table
            self._table_grow_time[node.family] = self.scheduler.time()
        # offer to searches whenever the node is NEW to us — even if its
        # bucket was full and the table only cached it — or confirmed.
        # The reference's RoutingTable::onNewNode returns true on the
        # bucket-full path too (routing_table.cpp:254-261); gating on
        # table admission starved searches of discovered nodes once
        # buckets filled (found via the live-vs-simulator hop parity
        # check, tests/test_hop_parity.py).
        if not was_known or confirm:
            self._try_search_insert(node)
        if confirm:
            self._update_status(node.family, debounce=True)

    def _on_reported_addr(self, _id: InfoHash, addr: Optional[SockAddr]) -> None:
        """Collect peers' echoes of our public address
        (↔ Dht::reportedAddr, src/dht.cpp:152-164)."""
        if addr is None or not addr.port:
            return
        for i, (count, a) in enumerate(self.reported_addr):
            if a == addr:
                self.reported_addr[i] = (count + 1, a)
                return
        if len(self.reported_addr) < 32:
            self.reported_addr.append((1, addr))

    def get_public_address(self, family: int = 0) -> List[SockAddr]:
        """(src/dht.cpp:103-115)"""
        ordered = sorted(self.reported_addr, key=lambda e: -e[0])
        return [a for _, a in ordered if not family or a.family == family]

    # ============================================================== the tokens
    def _rotate_secrets(self) -> None:
        self._oldsecret = self._secret
        self._secret = os.urandom(8)
        self.scheduler.add(self.scheduler.time() + random.uniform(15 * 60, 45 * 60),
                           self._rotate_secrets)

    def _make_token(self, addr: SockAddr, old: bool) -> bytes:
        """sha256(secret ‖ ip ‖ port) (↔ Dht::makeToken,
        src/dht.cpp:1381-1411; crypto::hash picks SHA-256 for 32 B)."""
        if addr.ip is None:
            return b""
        secret = self._oldsecret if old else self._secret
        h = hashlib.sha256()
        h.update(secret)
        h.update(addr.ip.packed)
        h.update(addr.port.to_bytes(2, "big"))
        return h.digest()[:TOKEN_SIZE]

    def _token_match(self, token: bytes, addr: Optional[SockAddr]) -> bool:
        if addr is None or len(token) != TOKEN_SIZE:
            return False
        return token == self._make_token(addr, False) or \
            token == self._make_token(addr, True)

    # ========================================================== search driving
    def _edit_step(self, sr: Search, t: float) -> None:
        if sr.next_search_step is not None:
            sr.next_search_step = self.scheduler.edit(sr.next_search_step, t)
        else:
            sr.next_search_step = self.scheduler.add(
                t, lambda: self._search_step(sr))

    def _search(self, target: InfoHash, af: int, get_cb=None, query_cb=None,
                done_cb=None, f: Optional[Filter] = None,
                q: Optional[Query] = None) -> Optional[Search]:
        """Find-or-create the search and attach a Get op
        (↔ Dht::search, src/dht.cpp:681-746)."""
        if not self.is_running(af):
            if done_cb:
                done_cb(False, [])
            return None
        srs = self.searches[af]
        keys = self._search_keys[af]
        sr = srs.get(target)
        if sr is not None:
            sr.done = False
            sr.expired = False
        else:
            if sum(len(s) for s in self.searches.values()) >= MAX_SEARCHES:
                # reuse a finished search slot (src/dht.cpp:703-717)
                victim = next(
                    (key for key, s in srs.items()
                     if (s.done or s.expired) and not s.announce
                     and not s.listeners), None)
                if victim is None:
                    log.error("[search %s] maximum number of searches "
                              "reached", target,
                              extra={"dht_hash": bytes(target)})
                    if done_cb:
                        done_cb(False, [])
                    return None
                old = srs.pop(victim)
                old.stop()
                keys.remove(bytes(victim))
            self._search_id = (self._search_id + 1) & 0xFFFF or 1
            sr = Search(target, af, self._search_id,
                        clock=self.scheduler.time)
            srs[target] = sr
            insort(keys, bytes(target))

        # adopt the calling op's trace context (runner ops activate it
        # around the posted closure) UNCONDITIONALLY: a reused search
        # re-parents its remaining hops under the newest op — and an
        # untraced op clears a finished trace's context, so its RPCs
        # never leak spans (or wire bytes) into a trace that already
        # ended (found by review)
        sr.trace_ctx = tracing.current()

        if get_cb or query_cb:
            sr.callbacks.append(Get(
                start=self.scheduler.time(), filter=f,
                query=q if q is not None else Query(),
                query_cb=query_cb, get_cb=get_cb, done_cb=done_cb))
        self._refill(sr)
        self._edit_step(sr, self.scheduler.time())
        return sr

    def _refill(self, sr: Search) -> int:
        """Seed/refresh the candidate set from the routing table — the
        batched device top-k instead of the reference's scalar cache walk
        (↔ Dht::refill, src/dht.cpp:656-677).

        Round 12: with the ingest wave builder enabled the resolve rides
        the next shared ``[Q]`` wave (fill- or deadline-triggered)
        instead of paying a per-search padded launch; the nodes land via
        :meth:`_refill_apply` and the search re-steps itself.  The
        ``ingest_batching="off"`` path below is byte-for-byte the
        pre-round-12 per-op dispatch.

        Round 16 (ISSUE-11): a PURE-GET refill is cache-eligible — the
        wave builder probes the hot-value cache in one batched
        XOR-compare launch before ``_launch`` and a hit completes the
        get via :meth:`_refill_cache_hit` without the search ever
        joining the ``[Q]`` lookup; the batching-off path takes the
        identical decision through the host-side membership test
        (``hotcache.serve_one``)."""
        now = self.scheduler.time()
        sr.refill_time = now
        cacheable = self._cache_eligible(sr)
        if self.wave_builder.enabled:
            if not sr.refill_pending:
                sr.refill_pending = True
                self.wave_builder.submit(
                    sr.id, sr.af, SEARCH_NODES,
                    lambda nodes, _sr=sr: self._refill_apply(_sr, nodes),
                    cache_cb=(lambda values, _sr=sr:
                              self._refill_cache_hit(_sr, values))
                    if cacheable else None)
            return 0
        if cacheable:
            vals = self.hotcache.serve_one(sr.id)
            if vals is not None:
                self.keyspace.observe_hashes([sr.id], source="cache")
                self._refill_cache_hit(sr, vals)
                return 0
        return self._refill_insert(
            sr, self.find_closest_nodes(sr.id, sr.af, SEARCH_NODES))

    def _cache_eligible(self, sr: Search) -> bool:
        """Only PURE-GET searches may be served from the hot-value
        cache: an announce needs real closest nodes to put to, a listen
        needs live subscriptions, and a field query projects server-
        side — all of those always ride the wave.  Pinned result-
        equivalent cache-on vs cache-off in tests/test_hotcache.py."""
        hc = self.hotcache
        if hc is None or not hc.enabled:
            return False
        if sr.announce or sr.listeners or not sr.callbacks:
            return False
        return all(g.get_cb is not None and g.query_cb is None
                   for g in sr.callbacks)

    def _refill_cache_hit(self, sr: Search, values: List[Value]) -> None:
        """Serve a cache-eligible search from the hot-value cache: the
        cached values complete every pending get (through its own
        filter) exactly as :meth:`_search_step`'s completed-get block
        would, without the search joining a lookup launch.  The search
        object stays reusable — a later op on the same key re-opens it
        through the normal path.

        Eligibility is RE-CHECKED here: it was decided at submit time,
        and an announce/listen can join the search while the refill sat
        in the wave queue — swallowing that refill would leave the
        search with zero candidates and the put/listen would expire
        unserved (review finding).  A no-longer-eligible search falls
        through to the normal refill path instead."""
        sr.refill_pending = False
        if not self._cache_eligible(sr):
            self._refill(sr)
            if not sr.expired and not sr.done:
                self._edit_step(sr, self.scheduler.time())
            return
        completed = list(sr.callbacks)
        for get in completed:
            vals = [v for v in values
                    if get.filter is None or get.filter(v)]
            if get.get_cb and vals:
                get.get_cb(vals)
            sr.set_get_done(get)
            sr.callbacks.remove(get)
        for get in completed:
            for sn in sr.nodes:
                sn.get_status.pop(get.query, None)
                sn.pagination_queries.pop(get.query, None)
        if not sr.callbacks and not sr.announce and not sr.listeners:
            sr.set_done()

    def _refill_insert(self, sr: Search, nodes: List[Node]) -> int:
        now = self.scheduler.time()
        inserted = 0
        for n in nodes:
            if sr.insert_node(n, now):
                inserted += 1
        # fall back to the engine's interned-node cache when the table is
        # still empty (e.g. first bootstrap reply not yet confirmed)
        if not inserted and not sr.nodes:
            for n in self.engine.get_cached_nodes(sr.id, sr.af, SEARCH_NODES):
                if sr.insert_node(n, now):
                    inserted += 1
        return inserted

    def _refill_apply(self, sr: Search, nodes: List[Node]) -> None:
        """Scatter half of a coalesced refill: the wave that carried
        this search's resolve delivers its candidate rows; step the
        search at whatever round it is on (continuous batching — a
        search never blocks a wave, a wave never blocks a search)."""
        sr.refill_pending = False
        self._refill_insert(sr, nodes)
        if not sr.expired and not sr.done:
            self._edit_step(sr, self.scheduler.time())

    def _search_step(self, sr: Search) -> None:
        """One scheduler-driven step (↔ Dht::searchStep,
        src/dht.cpp:561-654)."""
        if sr.expired or sr.done:
            return
        now = self.scheduler.time()
        sr.step_time = now

        if sr.refill_time + NODE_EXPIRE_TIME < now and \
                len(sr.nodes) - sr.get_number_of_bad_nodes() < SEARCH_NODES:
            self._refill(sr)

        if sr.is_synced(now):
            if sr.callbacks or sr.announce:
                completed = [g for g in sr.callbacks if sr.is_done(g)]
                for get in completed:
                    sr.set_get_done(get)
                    sr.callbacks.remove(get)
                for get in completed:
                    for sn in sr.nodes:
                        sn.get_status.pop(get.query, None)
                        sn.pagination_queries.pop(get.query, None)
                sr.check_announced()
                if not sr.callbacks and not sr.announce and not sr.listeners:
                    sr.set_done()

            if sr.listeners:
                i = 0
                for sn in sr.nodes:
                    if not sn.is_synced(now):
                        continue
                    self._search_node_listen(sr, sn)
                    if not sn.candidate:
                        i += 1
                        if i == LISTEN_NODES:
                            break

            self._search_send_announce(sr)
            if not sr.callbacks and not sr.announce and not sr.listeners:
                sr.set_done()

        while sr.currently_solicited_node_count() < MAX_REQUESTED_SEARCH_NODES:
            if self._search_send_get_values(sr) is None:
                break

        # a refill in flight on the wave builder must finish before the
        # bad-node rule can expire the search: a freshly-admitted op's
        # candidate set is legitimately empty until its wave lands
        # (0 >= min(0, MAX) would expire it within one step otherwise)
        if not sr.refill_pending and \
                sr.get_number_of_consecutive_bad_nodes() >= min(
                len(sr.nodes), SEARCH_MAX_BAD_NODES):
            log.warning("[search %s] expired", sr.id,
                        extra={"dht_hash": bytes(sr.id)})
            sr.expire()
            self.connectivity_changed(sr.af)
            return

        # self-reschedule at the next announce/listen refresh so permanent
        # puts and listens refresh before remote expiry even when no other
        # traffic steps this search (live_search.Search.get_next_step_time)
        nxt = sr.get_next_step_time(now)
        if nxt < TIME_MAX:
            job = sr.next_search_step
            pending = job.time if (job is not None
                                   and not job.cancelled) else None
            if pending is None or nxt < pending:
                self._edit_step(sr, nxt)

    @_traced_search
    def _search_send_get_values(self, sr: Search,
                                pn: Optional[SearchNode] = None,
                                update: bool = True) -> Optional[SearchNode]:
        """Send the next solicitation (↔ Dht::searchSendGetValues,
        src/dht.cpp:312-378)."""
        if sr.done or sr.currently_solicited_node_count() \
                >= MAX_REQUESTED_SEARCH_NODES:
            return None
        now = self.scheduler.time()
        gets = sr.callbacks or [None]
        for get in gets:
            query = get.query if get is not None else _ANY_QUERY
            up = sr.get_last_get_time(query) \
                if (get is not None and update) else _NEVER
            n: Optional[SearchNode] = None
            if pn is not None and pn.can_get(now, up, query):
                n = pn
            else:
                for sn in sr.nodes:
                    if sn.can_get(now, up, query):
                        n = sn
                        break
            if get is None:
                # no pending get op: plain find_node sync probe
                if n is None:
                    return None
                n.get_status[query] = self.engine.send_find_node(
                    n.node, sr.id, -1,
                    self._mk_get_done(sr, query),
                    self._mk_get_expired(sr, query))
                return n
            if n is None:
                continue
            if query is not None and not query.select.empty():
                n.get_status[query] = self.engine.send_get_values(
                    n.node, sr.id, query, -1,
                    self._mk_get_done(sr, query),
                    self._mk_get_expired(sr, query))
            else:
                self._paginate(sr, query, n)
            return n
        return None

    def _mk_get_done(self, sr: Search, query: Query):
        def on_done(req: Request, answer: RequestAnswer):
            self._search_node_get_done(req, answer, sr, query)
        return on_done

    def _mk_get_expired(self, sr: Search, query: Query):
        def on_expired(req: Request, over: bool):
            sn = sr.get_node(req.node)
            if sn is not None:
                sn.candidate = not over
                if over:
                    sn.get_status.pop(query, None)
            self._edit_step(sr, self.scheduler.time())
        return on_expired

    def _search_node_get_done(self, req: Request, answer: RequestAnswer,
                              sr: Search, query: Query) -> None:
        """A node answered a get/find (↔ Dht::searchNodeGetDone,
        src/dht.cpp:212-240)."""
        now = self.scheduler.time()
        sr.insert_node(req.node, now, answer.ntoken)
        sn = sr.get_node(req.node)
        if sn is not None:
            # requests already satisfied by this answer need not be sent
            for g in sr.callbacks:
                if g.query.is_satisfied_by(query) and g.query != query:
                    sn.get_status[g.query] = cancelled_request()
            sync_time = sn.get_sync_time(now)
            if sn.sync_job is not None:
                sn.sync_job = self.scheduler.edit(sn.sync_job, sync_time)
            else:
                sn.sync_job = self.scheduler.add(
                    sync_time, lambda: self._search_step(sr))
        self._on_get_values_done(req.node, answer, sr, query)

    @_traced_search
    def _paginate(self, sr: Search, query: Query, n: SearchNode) -> None:
        """SELECT id probe, then per-id sub-gets — keeps every reply under
        the value-size packet cap (↔ Dht::paginate, src/dht.cpp:258-310)."""
        select_q = Query(Select().field(Field.ID), query.where)

        def on_select_done(req: Request, answer: RequestAnswer):
            if answer.fields:
                sn = sr.get_node(req.node)
                if sn is None:
                    return
                for fvi in answer.fields:
                    fv = fvi.index.get(Field.ID)
                    if fv is None or fv.value == Value.INVALID_ID:
                        continue
                    q_vid = Query(Select(), Where().id(fv.value))
                    sn.pagination_queries.setdefault(query, []).append(q_vid)
                    sn.get_status[q_vid] = self.engine.send_get_values(
                        req.node, sr.id, q_vid, -1,
                        self._mk_get_done(sr, query),
                        self._mk_get_expired(sr, q_vid))
            else:
                # peer ignored the projection: plain full answer
                self._search_node_get_done(req, answer, sr, query)

        n.pagination_queries.setdefault(query, []).append(select_q)
        # the per-id sub-gets are sent from the select reply callback —
        # restore the search's context around it (ISSUE-4)
        n.get_status[select_q] = self.engine.send_get_values(
            n.node, sr.id, select_q, -1,
            lambda r, a: tracing.run_with(sr.trace_ctx,
                                          lambda: on_select_done(r, a)),
            self._mk_get_expired(sr, select_q))

    def _on_get_values_done(self, node: Node, a: RequestAnswer, sr: Search,
                            orig_query: Optional[Query]) -> None:
        """Dispatch an answer's values to the search's get ops
        (↔ Dht::onGetValuesDone, src/dht.cpp:2163-2235)."""
        if a.ntoken:
            if a.values or a.fields:
                for get in sr.callbacks:
                    if not (get.get_cb or get.query_cb):
                        continue
                    if orig_query is not None and \
                            not get.query.is_satisfied_by(orig_query):
                        continue
                    if get.query_cb:
                        if a.fields:
                            get.query_cb(a.fields)
                        elif a.values:
                            get.query_cb([
                                FieldValueIndex(
                                    v, orig_query.select if orig_query
                                    else Select())
                                for v in a.values])
                    elif get.get_cb:
                        vals = [v for v in a.values
                                if get.filter is None or get.filter(v)]
                        if vals:
                            get.get_cb(vals)
        else:
            log.warning("[node %s] no token provided; blacklisting", node.id,
                        extra={"dht_hash": bytes(node.id)})
            self.engine.blacklist_node(node)

        if not sr.done:
            self._search_send_get_values(sr)
            self._edit_step(sr, self.scheduler.time())

    # ----------------------------------------------------------- announce path
    def _replica_k(self, key: InfoHash) -> int:
        """Adaptive replica set for ``key`` (ISSUE-11): closest-16
        while the key is in the hot-cache's hot set (widening relieves
        the storing-node bottleneck the way Kademlia §4.1 prescribes),
        closest-8 otherwise — and back to 8 the tick after the key
        decays out.  Consulted by the announce walk and the
        calendar-binned republish resolve; pinned vs a scalar oracle
        in tests/test_hotcache.py."""
        return self.hotcache.replica_k(key)

    @_traced_search
    def _search_send_announce(self, sr: Search) -> None:
        """Probe synced nodes with SELECT id,seq then put/refresh
        (↔ Dht::searchSendAnnounceValue, src/dht.cpp:380-485).

        Round 16: the replica walk counts to :meth:`_replica_k` (8, or
        16 for hot keys) instead of the fixed TARGET_NODES, and the
        search's candidate capacity widens by the same margin so the
        wider walk has candidates to reach — both re-evaluated per call,
        so a key decaying out of the hot set narrows automatically."""
        if not sr.announce:
            return
        now = self.scheduler.time()
        rk = self._replica_k(sr.id)
        sr.capacity = max(SEARCH_NODES,
                          rk + (SEARCH_NODES - TARGET_NODES))
        probe_query = Query(Select().field(Field.ID).field(Field.SEQ_NUM))
        i = 0
        for sn in sr.nodes:
            if not sn.is_synced(now):
                continue
            if not any(sn.get_announce_time(a.value.id) <= now
                       for a in sr.announce):
                # already announced/pending on this node: it still occupies
                # one of the k replica slots — count it so the walk can't
                # drift past the 8 closest while acks are in flight (the
                # reference skips without counting, dht.cpp:391-395, which
                # over-replicates under fast stepping; k-closest semantics
                # per routing_table.h:26)
                if not sn.candidate:
                    i += 1
                    if i == rk:
                        break
                continue

            def on_put_done(req: Request, answer: RequestAnswer):
                self._on_announce_done(req.node, answer, sr)
                self._search_step(sr)

            def on_put_expired(req: Request, over: bool):
                if over:
                    self._edit_step(sr, self.scheduler.time())

            def on_select_done(req: Request, answer: RequestAnswer,
                               _done=on_put_done, _exp=on_put_expired):
                now = self.scheduler.time()
                sr.insert_node(req.node, now, answer.ntoken)
                s = sr.get_node(req.node)
                if s is None:
                    return
                if not s.is_synced(now):
                    self._edit_step(sr, now)
                    return
                for a in sr.announce:
                    if s.get_announce_time(a.value.id) > now:
                        continue
                    has_value = False
                    seq_no = 0
                    for fvi in answer.fields:
                        fid = fvi.index.get(Field.ID)
                        if fid is not None and fid.value == a.value.id:
                            has_value = True
                            fseq = fvi.index.get(Field.SEQ_NUM)
                            seq_no = fseq.value if fseq is not None else 0
                            break
                    next_refresh = now + self.types.get_type(
                        a.value.type).expiration
                    if not has_value or seq_no < a.value.seq:
                        s.acked[a.value.id] = (
                            self.engine.send_announce_value(
                                s.node, sr.id, a.value,
                                None if a.permanent else a.created,
                                s.token, _done, _exp),
                            next_refresh)
                    elif has_value and a.permanent:
                        s.acked[a.value.id] = (
                            self.engine.send_refresh_value(
                                s.node, sr.id, a.value.id, s.token,
                                _done, _exp),
                            next_refresh)
                    else:
                        s.acked[a.value.id] = (acked_request(now),
                                               next_refresh)
                        self._edit_step(sr, now)

            sn.probe_query = probe_query
            # the select-done callback fires from the reply path (no
            # ambient context) and sends the put/refresh itself —
            # restore the search's context around it (ISSUE-4)
            sn.get_status[probe_query] = self.engine.send_get_values(
                sn.node, sr.id, probe_query, -1,
                lambda r, a, _cb=on_select_done: tracing.run_with(
                    sr.trace_ctx, lambda: _cb(r, a)),
                self._mk_get_expired(sr, probe_query))
            if not sn.candidate:
                i += 1
                if i == rk:
                    break

    def _on_announce_done(self, node: Node, answer: RequestAnswer,
                          sr: Search) -> None:
        """(↔ Dht::onAnnounceDone, src/dht.cpp:2362-2369)"""
        self._search_send_get_values(sr)
        sr.check_announced(answer.vid)

    # ------------------------------------------------------------- listen path
    @_traced_search
    def _search_node_listen(self, sr: Search, sn: SearchNode) -> None:
        """Maintain listen contracts on one synced node
        (↔ Dht::searchSynchedNodeListen, src/dht.cpp:487-557)."""
        now = self.scheduler.time()
        for list_token, sl in list(sr.listeners.items()):
            query = sl.query
            if sn.get_listen_time(query) > now:
                continue
            ls = sn.listen_status.get(query)
            if ls is None:
                from .live_search import CachedListenStatus

                def cache_cb(values, expired, _t=list_token):
                    l = sr.listeners.get(_t)
                    if l is not None:
                        vals = (values if l.filter is None
                                else [v for v in values if l.filter(v)])
                        if vals:
                            l.get_cb(vals, expired)

                ls = sn.listen_status[query] = CachedListenStatus(cache_cb)
                node = sn.node

                def expire_cache(_q=query, _n=node):
                    s = sr.get_node(_n)
                    if s is not None:
                        s.expire_values(_q, self.scheduler)
                ls.cache_expiration_job = self.scheduler.add(
                    TIME_MAX, expire_cache)

            def on_listen_done(req: Request, answer: RequestAnswer,
                               _q=query):
                self._edit_step(sr, self.scheduler.time())
                s = sr.get_node(req.node)
                if s is not None:
                    self.scheduler.add(s.get_listen_time(_q),
                                       lambda: self._search_step(sr))
                if not sr.done:
                    self._search_send_get_values(sr)

            def on_listen_expired(req: Request, over: bool, _q=query):
                self._edit_step(sr, self.scheduler.time())
                if over:
                    s = sr.get_node(req.node)
                    if s is not None:
                        s.listen_status.pop(_q, None)

            def on_socket_values(node: Node, msg, _q=query):
                """Unsolicited pushes on the listen socket."""
                self._edit_step(sr, self.scheduler.time())
                answer = RequestAnswer.from_msg(msg)
                sr.insert_node(node, self.scheduler.time(), answer.ntoken)
                s = sr.get_node(node)
                if s is not None:
                    s.on_values(_q, answer, self.types, self.scheduler)

            new_req = self.engine.send_listen(
                sn.node, sr.id, query, sn.token, ls.req,
                on_listen_done, on_listen_expired, on_socket_values)
            ls = sn.listen_status.get(query)
            if ls is not None and new_req is not None:
                ls.req = new_req

    # ================================================================ public API
    def get(self, key: InfoHash, get_cb=None, done_cb=None,
            f: Optional[Filter] = None, where: Optional[Where] = None) -> None:
        """Iterative value lookup over both families
        (↔ Dht::get, src/dht.cpp:980-1017)."""
        if not self.wave_builder.admit("get"):
            if done_cb:
                done_cb(False, [])
            return
        log.debug("[search %s] get", key, extra={"dht_hash": bytes(key)})
        q = Query(Select(), where or Where())
        f = Filters.chain(f, q.where.get_filter())
        # captured BEFORE the search starts: an invalidation landing
        # while this get is in flight bumps the key's token and the
        # fill-on-get offer below is rejected (freshness)
        offer_token = self.hotcache.offer_token(key)
        # done when the user stops us or both family searches finish;
        # ok = user-stop or either search completing (dht.cpp:952-978)
        state = {"done": False, "stop": False, "done4": False, "done6": False,
                 "ok4": False, "ok6": False, "values": [], "nodes": []}

        def maybe_done(nodes: List[Node]):
            state["nodes"].extend(nodes)
            if state["done"]:
                return
            if state["stop"] or (state["done4"] and state["done6"]):
                state["done"] = True
                # fill-on-get (ISSUE-11, the Kademlia lookup-path
                # caching move): a completed get on a currently-hot,
                # not-yet-cached key seeds the hot-value cache with the
                # observed value set — the next hot get serves from it.
                # ONLY unfiltered gets may seed: a where/user filter
                # makes state["values"] a SUBSET of the key's value
                # set, and caching it would drop values from later
                # unfiltered gets (review finding).  The offer token
                # rejects a seed whose key was invalidated by a put
                # while this get was in flight — the stale pre-put set
                # must not re-enter through the fill path (review
                # finding).
                if state["values"] and f is None \
                        and self.hotcache.wants(key):
                    self.hotcache.offer(key, list(state["values"]),
                                        token=offer_token)
                if done_cb:
                    done_cb(state["stop"] or state["ok4"] or state["ok6"],
                            state["nodes"])

        def gcb(values: List[Value]) -> bool:
            if state["done"]:
                return False
            new = []
            for v in values:
                if any(sv is v or sv == v for sv in state["values"]):
                    continue
                if f is None or f(v):
                    new.append(v)
            if new:
                state["values"].extend(new)
                if get_cb is not None and not get_cb(new):
                    state["stop"] = True   # user said stop
            maybe_done([])
            return not state["stop"]

        local = self.get_local(key, f)
        if local:
            gcb(local)

        def mk_done(flag: str, ok_flag: str):
            def cb(ok: bool, nodes: List[Node]):
                state[flag] = True
                state[ok_flag] = ok
                maybe_done(nodes)
            return cb

        # preset non-running families FIRST (the put() discipline): a
        # cache-served get completes SYNCHRONOUSLY inside _search on
        # the batching-off path (round 16), and its done callback must
        # see the final flag state or the op never reports done
        ran = False
        families = ((_socket.AF_INET, "done4", "ok4"),
                    (_socket.AF_INET6, "done6", "ok6"))
        for af, flag, _ok in families:
            if not self.is_running(af):
                state[flag] = True
        for af, flag, ok_flag in families:
            if self.is_running(af):
                ran = True
                self._search(key, af, get_cb=gcb,
                             done_cb=mk_done(flag, ok_flag), f=f, q=q)
        if not ran:
            maybe_done([])

    def query(self, key: InfoHash, query_cb, done_cb=None,
              q: Optional[Query] = None) -> None:
        """Remote field query (↔ Dht::query, src/dht.cpp:1019-1064)."""
        if not self.wave_builder.admit("query"):
            if done_cb:
                done_cb(False, [])
            return
        q = q or Query()
        f = q.where.get_filter()
        state = {"done": False, "done4": False, "done6": False,
                 "fields": [], "nodes": []}

        def maybe_done(nodes):
            state["nodes"].extend(nodes)
            if not state["done"] and state["done4"] and state["done6"]:
                state["done"] = True
                if done_cb:
                    done_cb(bool(state["fields"]), state["nodes"])

        def qcb(fields: List[FieldValueIndex]) -> bool:
            if state["done"]:
                return False
            new = []
            for fv in fields:
                if any(fv.contained_in(sf) for sf in state["fields"]):
                    continue
                state["fields"] = [sf for sf in state["fields"]
                                   if not sf.contained_in(fv)]
                new.append(fv)
            if new:
                state["fields"].extend(new)
                query_cb(new)
            return True

        local = self.get_local(key, f)
        if local:
            qcb([FieldValueIndex(v, q.select) for v in local])

        def mk_done(flag: str):
            def cb(ok: bool, nodes):
                state[flag] = True
                maybe_done(nodes)
            return cb

        for af, flag in ((_socket.AF_INET, "done4"),
                         (_socket.AF_INET6, "done6")):
            if self.is_running(af):
                self._search(key, af, query_cb=qcb, done_cb=mk_done(flag), q=q)
            else:
                state[flag] = True
        maybe_done([])

    def put(self, key: InfoHash, value: Value, done_cb=None,
            created: Optional[float] = None, permanent: bool = False) -> None:
        """Store a value on the k closest nodes
        (↔ Dht::put, src/dht.cpp:913-946)."""
        if not self.wave_builder.admit("put"):
            if done_cb:
                done_cb(False, [])
            return
        if value.id == Value.INVALID_ID:
            value.id = random_value_id()
        # freshness (ISSUE-11): invalidate BEFORE the announce, even
        # when the local store rejects the value (full/over-quota) —
        # the put is still propagating to the network, and a stale
        # cache hit must not outlive it
        self.hotcache.invalidate(key)
        state = {"done": False, "done4": False, "done6": False,
                 "ok4": False, "ok6": False}

        def mk_done(flag: str, ok_flag: str):
            def cb(ok: bool, nodes: List[Node]):
                state[flag] = True
                state[ok_flag] = ok
                if done_cb and not state["done"] and \
                        state["done4"] and state["done6"]:
                    state["done"] = True
                    done_cb(state["ok4"] or state["ok6"], nodes)
            return cb

        # preset non-running families first so a synchronous callback from
        # _announce (value already announced / search unavailable) sees the
        # final flag state and can complete the put
        families = ((_socket.AF_INET, "done4", "ok4"),
                    (_socket.AF_INET6, "done6", "ok6"))
        for af, flag, _ok in families:
            if not self.is_running(af):
                state[flag] = True
        for af, flag, ok_flag in families:
            if self.is_running(af):
                self._announce(key, af, value, mk_done(flag, ok_flag),
                               created, permanent)
        if done_cb and not state["done"] and state["done4"] and state["done6"]:
            state["done"] = True
            done_cb(state["ok4"] or state["ok6"], [])
        if permanent:
            self._schedule_local_refresh(key, value)

    def _schedule_local_refresh(self, key: InfoHash, value: Value) -> None:
        """Keep the *local* copy of a permanent put alive: remote copies
        are refreshed by the announce path (send_refresh_value), but the
        putter's own storage would hit its TTL otherwise.  Runs until the
        permanent announce is cancelled on every family.  One chain per
        (key, vid) — re-puts of the same value reuse the live chain."""
        ttl = self.types.get_type(value.type).expiration
        vid = value.id
        if (key, vid) in self._local_refresh_jobs:
            return

        def local_expiration() -> Optional[float]:
            st = self.store.get(key)
            if st is not None:
                for vs in st.values:
                    if vs.data.id == vid:
                        return vs.expiration
            return None

        def arm(at: float) -> None:
            now = self.scheduler.time()
            self._local_refresh_jobs[(key, vid)] = self.scheduler.add(
                max(at, now + 1.0), local_refresh)

        def local_refresh():
            still = any(
                a.permanent and a.value.id == vid
                for srs in self.searches.values()
                for sr in ((srs.get(key),) if srs.get(key) else ())
                for a in sr.announce)
            if not still:
                self._local_refresh_jobs.pop((key, vid), None)
                return
            now = self.scheduler.time()
            st = self.store.get(key)
            new_exp = (st.refresh(now, vid, key)
                       if st is not None else None)
            if new_exp is None:
                # local copy is gone (swept or evicted) while the
                # permanent announce lives: re-store it
                self.storage_store(key, value, now)
                new_exp = local_expiration()
            if new_exp is not None:
                self._calendar_add(key, new_exp)
                arm(new_exp - REANNOUNCE_MARGIN)
            else:
                arm(now + max(ttl - REANNOUNCE_MARGIN, 1.0))

        exp = local_expiration()
        arm((exp - REANNOUNCE_MARGIN) if exp is not None
            else self.scheduler.time() + max(ttl - REANNOUNCE_MARGIN, 1.0))

    def _announce(self, key: InfoHash, af: int, value: Value, callback,
                  created: Optional[float], permanent: bool) -> None:
        """(↔ Dht::announce, src/dht.cpp:748-808)"""
        now = self.scheduler.time()
        created = min(now, created) if created is not None else now
        self.storage_store(key, value, created)

        sr = self._searches_of(af).get(key) or self._search(key, af)
        if sr is None:
            if callback:
                callback(False, [])
            return
        sr.done = False
        sr.expired = False
        existing = next((a for a in sr.announce if a.value.id == value.id),
                        None)
        if existing is None:
            sr.announce.append(Announce(permanent, value, created, callback))
            for sn in sr.nodes:
                sn.probe_query = None
                if value.id in sn.acked:
                    sn.acked[value.id] = (None, sn.acked[value.id][1])
        else:
            existing.permanent = permanent
            existing.created = created
            if existing.value != value:
                existing.value = value
                for sn in sr.nodes:
                    if value.id in sn.acked:
                        sn.acked[value.id] = (None, sn.acked[value.id][1])
                    sn.probe_query = None
            if sr.is_announced(value.id):
                if existing.callback:
                    existing.callback(True, [])
                    existing.callback = None
                if callback:
                    callback(True, [])
                return
            else:
                if existing.callback:
                    existing.callback(False, [])
                existing.callback = callback
        self._edit_step(sr, now)

    def listen(self, key: InfoHash, cb, f: Optional[Filter] = None,
               where: Optional[Where] = None) -> int:
        """Subscribe to values under a key (↔ Dht::listen,
        src/dht.cpp:827-867).  Returns a token for cancel_listen.

        Returns ``None`` when ingest backpressure sheds the op at
        admission (round 12) — never by dropping an established
        listener.  Distinct from the pre-existing ``0`` return, which
        means the callback consumed locally-stored values and stopped
        (a *satisfied* listen, not a refused one); callers that only
        care about "is there a live subscription" can keep testing
        truthiness, the runner distinguishes the two."""
        if not self.wave_builder.admit("listen"):
            return None
        log.debug("[search %s] listen", key, extra={"dht_hash": bytes(key)})
        q = Query(Select(), where or Where())
        self._listener_token += 1
        token = self._listener_token
        gcb = OpValueCache.cache_callback(cb)
        filt = Filters.chain(f, q.where.get_filter())

        token_local = 0
        st = self.store.get(key)
        if st is None and len(self.store) < self.max_store_keys:
            st = self.store[key] = Storage(self.scheduler.time()
                                           + MAX_STORAGE_MAINTENANCE_EXPIRE_TIME)
        if st is not None:
            if not st.empty():
                vals = st.get(filt)
                if vals and not gcb(vals, False):
                    return 0
            st.listener_token += 1
            token_local = st.listener_token
            st.local_listeners[token_local] = LocalListener(q, filt, gcb)
            self._listener_sync(key, st)

        token4 = self._listen_to(key, _socket.AF_INET, gcb, filt, q)
        token6 = self._listen_to(key, _socket.AF_INET6, gcb, filt, q)
        self.listeners[token] = (token_local, token4, token6)
        return token

    def _listen_to(self, key: InfoHash, af: int, cb, f: Optional[Filter],
                   q: Query) -> int:
        """(↔ Dht::listenTo, src/dht.cpp:810-825)"""
        if not self.is_running(af):
            return 0
        sr = self._searches_of(af).get(key) or self._search(key, af)
        if sr is None:
            return 0
        return sr.add_listener(
            cb, f, q, self.scheduler,
            lambda: self._edit_step(sr, self.scheduler.time()))

    def cancel_listen(self, key: InfoHash, token: int) -> bool:
        """(↔ Dht::cancelListen, src/dht.cpp:869-895)"""
        entry = self.listeners.pop(token, None)
        if entry is None:
            return False
        token_local, token4, token6 = entry
        st = self.store.get(key)
        if st is not None and token_local:
            st.local_listeners.pop(token_local, None)
            self._listener_sync(key, st)
        for af, t in ((_socket.AF_INET, token4), (_socket.AF_INET6, token6)):
            sr = self._searches_of(af).get(key)
            if sr is not None and t:
                sr.cancel_listen_token(t, self.scheduler)
        return True

    def get_put(self, key: InfoHash, vid: Optional[int] = None):
        """Pending announced values (↔ Dht::getPut, src/dht.cpp:1076-1120)."""
        if vid is None:
            out = []
            for srs in self.searches.values():
                sr = srs.get(key)
                if sr is not None:
                    out.extend(a.value for a in sr.announce)
            return out
        for srs in self.searches.values():
            sr = srs.get(key)
            if sr is not None:
                for a in sr.announce:
                    if a.value.id == vid:
                        return a.value
        return None

    def cancel_put(self, key: InfoHash, vid: int) -> bool:
        """(↔ Dht::cancelPut, src/dht.cpp:1122-1144)"""
        cancelled = False
        for srs in self.searches.values():
            sr = srs.get(key)
            if sr is not None:
                before = len(sr.announce)
                sr.announce = [a for a in sr.announce if a.value.id != vid]
                cancelled |= len(sr.announce) != before
        return cancelled

    # ================================================================= storage
    def get_local(self, key: InfoHash, f: Optional[Filter] = None
                  ) -> List[Value]:
        st = self.store.get(key)
        return st.get(f) if st is not None else []

    def get_local_by_id(self, key: InfoHash, vid: int) -> Optional[Value]:
        st = self.store.get(key)
        return st.get_by_id(vid) if st is not None else None

    def storage_store(self, key: InfoHash, value: Value, created: float,
                      sa: Optional[SockAddr] = None) -> bool:
        """(↔ Dht::storageStore, src/dht.cpp:1193-1228)"""
        log.debug("[store %s] storing value %x", key, value.id,
                  extra={"dht_hash": bytes(key)})
        now = self.scheduler.time()
        created = min(created, now)
        expiration = created + self.types.get_type(value.type).expiration
        if expiration < now:
            return False
        st = self.store.get(key)
        if st is None:
            if len(self.store) >= self.max_store_keys:
                return False
            st = self.store[key] = Storage(now)
            if self.maintain_storage:
                st.maintenance_time = now + MAX_STORAGE_MAINTENANCE_EXPIRE_TIME
                st.maintenance_armed = True
                self._calendar_add(key, st.maintenance_time)
        bucket = None
        if sa is not None:
            bucket = self.store_quota.setdefault(_quota_key(sa),
                                                 StorageBucket())
        vs, diff = st.store(key, value, created, expiration, bucket)
        if vs is not None:
            self.total_store_size += diff.size_diff
            self.total_values += diff.values_diff
            self._calendar_add(key, expiration)
            # keyspace observatory (ISSUE-10): stored-key puts count as
            # traffic too — buffered host-side, flushed into the next
            # wave's one scatter-add launch (never a launch of its own)
            self.keyspace.note_stored(key)
            # hot-cache freshness (ISSUE-11): an observed put — local
            # API put or incoming announce — invalidates the cached
            # entry, so the NEXT get takes the full path and can never
            # be served the stale value set
            self.hotcache.invalidate(key)
            if self.total_store_size > self.max_store_size:
                self._expire_store_all()
            self._storage_changed(key, st, vs.data, diff.values_diff > 0)
        return vs is not None or diff.values_diff == 0

    def _storage_changed(self, key: InfoHash, st: Storage, value: Value,
                         new_value: bool) -> None:
        """Notify local + remote listeners of a new value
        (↔ Dht::storageChanged, src/dht.cpp:1149-1191).

        Round 24 (ISSUE-20): with ``listen_batching="on"`` the put is
        BUFFERED on the listener table instead — the next ingest
        wave's single ``listener_match`` launch answers which buffered
        keys have listeners, and :meth:`flush_listener_wave`
        dispatches one coalesced callback/``tell_listener`` per wave
        per listener (same values, same per-listener order as the
        synchronous body below — pinned in tests/test_listener.py).
        This also batches the request-handler re-storage loops
        (``_on_announce``'s per-value ``storage_store``): a
        listen-triggered store now rides the wave cadence instead of
        probing listener dicts inside the handler."""
        if self.listener_table.note_stored(bytes(key), value, new_value):
            return
        if new_value:
            cbs = []
            for l in st.local_listeners.values():
                if l.filter is None or l.filter(value):
                    cbs.append(l.get_cb)
            for cb in cbs:
                cb([value], False)
        for node, node_listeners in list(st.listeners.items()):
            for sid, l in node_listeners.items():
                f = l.query.where.get_filter()
                if f is not None and not f(value):
                    continue
                ntoken = self._make_token(node.addr, False)
                self.engine.tell_listener(node, sid, key, 0, ntoken,
                                          [], [], [value], l.query)

    # ------------------------------------------------ wave-scale listen/push
    def _listener_live_count(self, kb: bytes) -> int:
        """The listener table's TTL-sweep re-count: how many live
        listeners (local + remote) a key has RIGHT NOW — the sweep
        refreshes rows that still have some and tombstones the rest
        (remote listeners expire silently in ``Storage.expire``; no
        cancel ever reaches :meth:`_listener_sync` for them)."""
        st = self.store.get(InfoHash(kb))
        if st is None:
            return 0
        return (len(st.local_listeners)
                + sum(len(m) for m in st.listeners.values()))

    def _listener_sync(self, key: InfoHash, st: Optional[Storage]) -> None:
        """Re-sync one key's row on the listener table after any
        listener-set mutation (listen/cancel/remote add/expiry sweep)
        — the table tracks exactly the keys with ≥1 listener, so the
        batched match and the synchronous probe answer identically."""
        lt = self.listener_table
        if not lt.enabled:
            return
        n = 0
        if st is not None:
            n = (len(st.local_listeners)
                 + sum(len(m) for m in st.listeners.values()))
        lt.sync_key(bytes(key), n)

    def _arm_listener_flush(self, delay: float) -> None:
        """The table's ``request_flush`` callback: guarantee a
        :meth:`flush_listener_wave` within ``delay`` seconds (idle
        nodes deliver on the deadline; busy nodes usually flush
        earlier, piggybacked on the next ingest wave fire)."""
        t = self.scheduler.time() + max(0.0, delay)
        job = self._listener_flush_job
        if job is not None and not job.cancelled:
            if job.time is not None and t < job.time:
                self._listener_flush_job = self.scheduler.edit(job, t)
        else:
            self._listener_flush_job = self.scheduler.add(
                t, self.flush_listener_wave)

    def flush_listener_wave(self) -> None:
        """Deliver every buffered stored put whose key has listeners:
        ONE ``listener_match`` launch over the buffer (the table's
        :meth:`~opendht_tpu.listeners.ListenerTable.flush`), then one
        coalesced dispatch per listener — local callbacks get the
        key's new values as a single batch, each remote ``(node,
        sid)`` socket gets a single ``tell_listener`` with the full
        filtered value list (↔ the per-value loop in the synchronous
        ``_storage_changed`` body; order within a key is arrival
        order, so per-listener ordering is preserved).  Runs as a
        scheduler job and from the wave builder's fire."""
        self._listener_flush_job = None
        lt = self.listener_table
        if not lt.pending():
            return
        dispatches = values_n = 0
        for kb, items in lt.flush():
            key = InfoHash(kb)
            st = self.store.get(key)
            if st is None:
                continue
            new_vals = [v for v, nv in items if nv]
            all_vals = [v for v, _nv in items]
            if new_vals:
                cbs = []
                for l in st.local_listeners.values():
                    vs = ([v for v in new_vals if l.filter(v)]
                          if l.filter is not None else list(new_vals))
                    if vs:
                        cbs.append((l.get_cb, vs))
                for cb, vs in cbs:
                    cb(vs, False)
                    dispatches += 1
                    values_n += len(vs)
            for node, node_listeners in list(st.listeners.items()):
                for sid, l in node_listeners.items():
                    f = l.query.where.get_filter()
                    vs = ([v for v in all_vals if f(v)]
                          if f is not None else list(all_vals))
                    if not vs:
                        continue
                    ntoken = self._make_token(node.addr, False)
                    self.engine.tell_listener(node, sid, key, 0, ntoken,
                                              [], [], vs, l.query)
                    dispatches += 1
                    values_n += len(vs)
        lt.note_delivered(dispatches, values_n)

    def _storage_add_listener(self, key: InfoHash, node: Node,
                              socket_id: int, query: Query) -> None:
        """(↔ Dht::storageAddListener, src/dht.cpp:1230-1253)"""
        now = self.scheduler.time()
        st = self.store.get(key)
        if st is None:
            if len(self.store) >= self.max_store_keys:
                return
            st = self.store[key] = Storage(now)
        node_listeners = st.listeners.setdefault(node, {})
        l = node_listeners.get(socket_id)
        if l is None:
            vals = st.get(query.where.get_filter())
            if vals:
                closest4 = self.find_closest_nodes(key, _socket.AF_INET)
                closest6 = self.find_closest_nodes(key, _socket.AF_INET6)
                self.engine.tell_listener(
                    node, socket_id, key, WANT4 | WANT6,
                    self._make_token(node.addr, False),
                    closest4, closest6, vals, query)
            node_listeners[socket_id] = Listener(now, query, socket_id)
            self._listener_sync(key, st)
        else:
            l.refresh(now, query)
            self._listener_sync(key, st)

    def _expire_storage(self, key: InfoHash) -> None:
        st = self.store.get(key)
        if st is not None:
            self._expire_store_one(key, st)

    def _expire_store_one(self, key: InfoHash, st: Storage) -> None:
        """(↔ Dht::expireStore(iterator), src/dht.cpp:1255-1297)"""
        size_diff, expired = st.expire(key, self.scheduler.time())
        self.total_store_size += size_diff
        self.total_values -= len(expired)
        # the expiry sweep may have dropped stale remote listeners —
        # re-sync the key's listener-table row (round 24)
        self._listener_sync(key, st)
        if expired:
            # a cached entry may hold the just-expired values; drop it
            # (the tick re-admits from the store's surviving set)
            self.hotcache.invalidate(key)
            vids = [v.id for v in expired]
            for node, node_listeners in list(st.listeners.items()):
                for sid in node_listeners:
                    ntoken = self._make_token(node.addr, False)
                    self.engine.tell_listener_expired(node, sid, key,
                                                      ntoken, vids)
            for l in list(st.local_listeners.values()):
                l.get_cb(expired, True)

    def _expire_store_all(self) -> None:
        """Expiry sweep + per-IP quota enforcement
        (↔ Dht::expireStore(), src/dht.cpp:1299-1348)."""
        for key in list(self.store):
            st = self.store[key]
            self._expire_store_one(key, st)
            if st.empty() and not st.listeners and not st.local_listeners:
                del self.store[key]
                self._listener_sync(key, None)
        while self.total_store_size > self.max_store_size:
            if not self.store_quota:
                log.warning("no space left: local data consumes all quota")
                break
            largest_key, largest = max(self.store_quota.items(),
                                       key=lambda kv: kv[1].size)
            if largest.size == 0:
                break
            oldest = largest.get_oldest()
            if oldest is None:
                break
            key, vid = oldest
            st = self.store.get(key)
            if st is None:
                break
            diff = st.remove(key, vid)
            self.total_store_size += diff.size_diff
            self.total_values += diff.values_diff
            if not diff.values_diff:
                break
        for k in [k for k, b in self.store_quota.items() if b.size == 0]:
            del self.store_quota[k]

    # ------------------------------------------------- storage calendar
    def _calendar_add(self, key: InfoHash, when: float) -> None:
        """Enqueue `key` for a storage sweep (expiry + republish check)
        at `when`.  Keys binned to the same STORAGE_CALENDAR_QUANTUM
        share ONE scheduler job — the round-10 replacement for the
        per-key ``_data_persistence``/``_expire_storage`` jobs whose
        heap entries scaled with the stored-key count.  Bins round UP
        so the sweep never fires before the key is due."""
        b = -int(-when // STORAGE_CALENDAR_QUANTUM)          # ceil
        s = self._storage_calendar.get(b)
        if s is None:
            self._storage_calendar[b] = s = set()
            self.scheduler.add(b * STORAGE_CALENDAR_QUANTUM,
                               lambda: self._calendar_fire(b))
            self._m_calendar_bins.set(len(self._storage_calendar))
        s.add(key)

    def _calendar_fire(self, b: int) -> None:
        """One calendar bin came due: run value expiry per key, then
        republish EVERY due key through one batched resolve.

        Loss profile under a raising callback (a local listener's
        ``get_cb`` runs inside the expiry): the per-key jobs this bin
        replaced lost only the raising key, so the untouched remainder
        of the bin is re-binned for the next tick instead of being
        dropped with the popped set."""
        keys = self._storage_calendar.pop(b, None)
        self._m_calendar_bins.set(len(self._storage_calendar))
        if not keys:
            return
        now = self.scheduler.time()
        due = []
        pending = sorted(keys, key=bytes, reverse=True)
        try:
            while pending:
                key = pending.pop()
                self._expire_storage(key)
                st = self.store.get(key)
                # republish only keys storage_store ARMED (the reference
                # never maintains listen-created storages); due when
                # `maintenance_time <= now`: `<` (not `<=`) so a
                # discrete-event driver landing exactly on
                # maintenance_time still republishes and reschedules
                if st is not None and self.maintain_storage \
                        and st.maintenance_armed \
                        and not now < st.maintenance_time:
                    due.append(key)
        except BaseException:
            for key in pending:
                self._calendar_add(key, now)
            for key in due:
                self._calendar_add(key, now)
            raise
        if due:
            self._storage_maintenance_batched(due)

    def _data_persistence(self, key: InfoHash) -> None:
        """Republish one key's stored values toward closer nodes before
        expiry (↔ Dht::dataPersistence, src/dht.cpp:1840-1852).  Single-
        key entry kept for direct callers; the calendar sweep
        (:meth:`_calendar_fire`) batches whole due sets into one device
        resolve instead of scheduling this per key."""
        st = self.store.get(key)
        now = self.scheduler.time()
        # run when due; `<` (not `<=`) so a discrete-event driver that lands
        # exactly on maintenance_time still republishes and reschedules
        if st is None or now < st.maintenance_time:
            return
        self._storage_maintenance_batched([key])

    def _republish_predicate(self, keys: List[InfoHash], af: int,
                             ks: Optional[List[int]] = None
                             ) -> List[bool]:
        """The "no longer among the k closest" test for MANY keys from
        ONE batched closest-k resolve (↔ the per-key
        ``find_closest_nodes`` + ``xor_cmp`` in Dht::maintainStorage,
        src/dht.cpp:1854-1900).  For each key the last addr-servable
        row stands in for ``find_closest_nodes(key, af)[-1]``, so the
        decision agrees EXACTLY with the scalar path (same addr filter,
        same `< 0` strictness on ties; pinned in
        tests/test_maintenance.py) — including tables smaller than k
        (the last VALID row, not the padded k-th) and empty tables
        (no nodes ⇒ no republish, family keeps responsibility).

        ``ks`` (round 16) is the per-key replica set from
        :meth:`_replica_k` — the ONE resolve runs at ``max(ks)`` and
        each key's decision reads the last servable row WITHIN its own
        first ``ks[i]`` columns (the top-k prefix of a wider top-k is
        the narrower top-k, so a uniform ks == [8]*n is bit-identical
        to the pre-round-16 path — hot keys widen to 16 without a
        second launch)."""
        table = self._table(af)
        out = [False] * len(keys)
        if table is None or len(table) == 0 or not keys:
            return out
        if ks is None:
            ks = [TARGET_NODES] * len(keys)
        rows, _dist = table.find_closest(list(keys), k=max(ks),
                                         now=self.scheduler.time())
        last_rows = np.full(len(keys), -1, dtype=np.int64)
        for qi in range(rows.shape[0]):
            for j in range(min(ks[qi], rows.shape[1]) - 1, -1, -1):
                r = int(rows[qi, j])
                if r >= 0 and table.addr_of(r) is not None:
                    last_rows[qi] = r
                    break
        kth_ids = table.ids_of_rows(last_rows)
        for qi, key in enumerate(keys):
            if last_rows[qi] >= 0:
                out[qi] = key.xor_cmp(kth_ids[qi], self.myid) < 0
        return out

    def _storage_maintenance_batched(self, keys: List[InfoHash]) -> int:
        """Republish every due key (↔ Dht::dataPersistence +
        maintainStorage, src/dht.cpp:1840-1900) with ONE closest-k
        device resolve per address family for the WHOLE due set —
        K keys cost one lane-padded launch, not K (the round-10
        planner; same batching move as the PR-1/PR-2 lookup path).
        Announce fan-out, responsibility bookkeeping and the
        not-responsible-anywhere clear are per key, exactly as the
        scalar :meth:`_maintain_storage` does them."""
        keys = [k for k in keys if k in self.store]
        if not keys:
            return 0
        now = self.scheduler.time()
        self._m_maint_due.inc(len(keys))
        announced = 0
        still = {bytes(k): {af: True for af in self.tables} for k in keys}
        reg = telemetry.get_registry()
        # adaptive replica widening (ISSUE-11): keys in the hot set
        # resolve/replicate at closest-16, the rest at closest-8 — ONE
        # launch per family either way (the predicate resolves at
        # max(ks) and reads each key's own k-prefix), riding the same
        # calendar bins
        ks = [self._replica_k(k) for k in keys]
        widened = sum(1 for k_i in ks if k_i > TARGET_NODES)
        if widened:
            reg.counter("dht_cache_republish_widened_total").inc(widened)
        with reg.span("dht_maintenance_republish_seconds"):
            republish = {af: self._republish_predicate(keys, af, ks)
                         for af in self.tables}
        # re-schedule EVERY key before the announce fan-out: a raising
        # callback mid-announce must not silently end the whole due
        # set's maintenance (the per-key jobs lost only the raising
        # key).  maintenance_armed is NOT set here — storage_store owns
        # arming, so a direct _data_persistence call on a listen-created
        # storage republishes once without enrolling it forever (the
        # calendar fire keeps skipping unarmed keys)
        for key in keys:
            st = self.store.get(key)
            if st is not None:
                st.maintenance_time = now + MAX_STORAGE_MAINTENANCE_EXPIRE_TIME
                self._calendar_add(key, st.maintenance_time)
        for af in self.tables:
            for key, do in zip(keys, republish[af]):
                if not do:
                    continue
                st = self.store.get(key)
                if st is None:
                    continue
                for vs in st.values:
                    vt = self.types.get_type(vs.data.type)
                    if vs.created + vt.expiration > \
                            now + MAX_STORAGE_MAINTENANCE_EXPIRE_TIME:
                        self._announce(key, af, vs.data, None,
                                       vs.created, False)
                        announced += 1
                still[bytes(key)][af] = False
        for key in keys:
            st = self.store.get(key)
            if st is None:
                continue
            if self.tables and not any(still[bytes(key)].values()):
                diff = st.clear(key)
                self.total_store_size += diff.size_diff
                self.total_values += diff.values_diff
        self._m_maint_republished.inc(announced)
        tr = tracing.get_tracer()
        if tr.enabled:
            tr.event("maintenance_republish", due=len(keys),
                     announced=announced)
        return announced

    def _maintain_storage(self, key: InfoHash, st: Storage,
                          force: bool = False, done_cb=None) -> int:
        """(↔ Dht::maintainStorage, src/dht.cpp:1854-1900)"""
        now = self.scheduler.time()
        announced = 0
        still_responsible = {af: True for af in self.tables}
        for af in self.tables:
            nodes = self.find_closest_nodes(key, af)
            if not nodes:
                continue
            if force or key.xor_cmp(nodes[-1].id, self.myid) < 0:
                for vs in st.values:
                    vt = self.types.get_type(vs.data.type)
                    if force or vs.created + vt.expiration > \
                            now + MAX_STORAGE_MAINTENANCE_EXPIRE_TIME:
                        self._announce(key, af, vs.data, done_cb,
                                       vs.created, False)
                        announced += 1
                still_responsible[af] = False
        if self.tables and not any(still_responsible.values()):
            diff = st.clear(key)
            self.total_store_size += diff.size_diff
            self.total_values += diff.values_diff
        return announced

    # ========================================================== RPC handlers
    def _on_error(self, req: Request, e: DhtProtocolException) -> None:
        """(↔ Dht::onError, src/dht.cpp:2089-2111)"""
        node = req.node
        if e.code == DhtProtocolException.UNAUTHORIZED:
            log.warning("[node %s] token flush", node.id,
                        extra={"dht_hash": bytes(node.id)})
            node.auth_error()
            node.cancel_request(req)
            table = self._table(node.family)
            if table is not None:
                table.on_auth_error(node.id)
            for sr in self._searches_of(node.family).values():
                for sn in sr.nodes:
                    if sn.node is not node:
                        continue
                    sn.token = b""
                    sn.last_get_reply = _NEVER
                    self._search_send_get_values(sr)
                    self._edit_step(sr, self.scheduler.time())
                    break
        elif e.code == DhtProtocolException.NOT_FOUND:
            node.cancel_request(req)

    def _on_ping(self, _node: Node) -> RequestAnswer:
        return RequestAnswer()

    def _on_find_node(self, node: Node, target: InfoHash, want: int
                      ) -> RequestAnswer:
        """(↔ Dht::onFindNode, src/dht.cpp:2126-2138)"""
        answer = RequestAnswer()
        answer.ntoken = self._make_token(node.addr, False)
        if want < 0:
            want = WANT4 if node.family == _socket.AF_INET else WANT6
        if want & WANT4:
            answer.nodes4 = self.find_closest_nodes(target, _socket.AF_INET)
        if want & WANT6:
            answer.nodes6 = self.find_closest_nodes(target, _socket.AF_INET6)
        return answer

    def _on_get_values(self, node: Node, key: InfoHash, _want: int,
                       query: Query) -> RequestAnswer:
        """(↔ Dht::onGetValues, src/dht.cpp:2140-2161)"""
        if not key:
            raise DhtProtocolException(
                DhtProtocolException.NON_AUTHORITATIVE_INFORMATION,
                DhtProtocolException.GET_NO_INFOHASH)
        answer = RequestAnswer()
        answer.ntoken = self._make_token(node.addr, False)
        answer.nodes4 = self.find_closest_nodes(key, _socket.AF_INET)
        answer.nodes6 = self.find_closest_nodes(key, _socket.AF_INET6)
        st = self.store.get(key)
        if st is not None and not st.empty():
            answer.values = st.get(query.where.get_filter())
        return answer

    def _on_listen(self, node: Node, key: InfoHash, token: bytes,
                   socket_id: int, query: Query) -> RequestAnswer:
        """(↔ Dht::onListen, src/dht.cpp:2237-2254)"""
        if not key:
            raise DhtProtocolException(
                DhtProtocolException.NON_AUTHORITATIVE_INFORMATION,
                DhtProtocolException.LISTEN_NO_INFOHASH)
        if not self._token_match(token, node.addr):
            raise DhtProtocolException(DhtProtocolException.UNAUTHORIZED,
                                       DhtProtocolException.LISTEN_WRONG_TOKEN)
        self._storage_add_listener(key, node, socket_id, query)
        return RequestAnswer()

    def _on_announce(self, node: Node, key: InfoHash, token: bytes,
                     values: List[Value], created: Optional[float]
                     ) -> RequestAnswer:
        """(↔ Dht::onAnnounce, src/dht.cpp:2272-2339)"""
        if not key:
            raise DhtProtocolException(
                DhtProtocolException.NON_AUTHORITATIVE_INFORMATION,
                DhtProtocolException.PUT_NO_INFOHASH)
        if not self._token_match(token, node.addr):
            raise DhtProtocolException(DhtProtocolException.UNAUTHORIZED,
                                       DhtProtocolException.PUT_WRONG_TOKEN)
        # store only if we're plausibly among the SEARCH_NODES closest
        # (src/dht.cpp:2290-2298) — one batched device call.  Keys hot
        # in THIS node's observatory skip the too-far rejection
        # (ISSUE-11): the widened closest-16 announce fan-out reaches
        # nodes past the closest-8, and refusing their stores would
        # defeat the replica widening the hot set asked for.
        table = self._table(node.family)
        if table is not None and len(table) > 0 \
                and not self.hotcache.is_hot(key):
            rows, _ = table.find_closest([key], k=SEARCH_NODES,
                                         now=self.scheduler.time())
            rows = rows[0][rows[0] >= 0]
            if len(rows) >= TARGET_NODES:
                kth = table.id_of(int(rows[-1]))
                if key.xor_cmp(kth, self.myid) < 0:
                    log.debug("[store %s] announce too far from target", key,
                          extra={"dht_hash": bytes(key)})
                    return RequestAnswer()
        now = self.scheduler.time()
        created = min(created, now) if created is not None else now
        for v in values:
            if v.id == Value.INVALID_ID:
                raise DhtProtocolException(
                    DhtProtocolException.NON_AUTHORITATIVE_INFORMATION,
                    DhtProtocolException.PUT_INVALID_ID)
            lv = self.get_local_by_id(key, v.id)
            if lv is not None:
                if lv != v:
                    vt = self.types.get_type(lv.type)
                    if vt.edit_policy(key, lv, v, node.id, node.addr):
                        self.storage_store(key, v, created, node.addr)
            else:
                vt = self.types.get_type(v.type)
                if vt.store_policy(key, v, node.id, node.addr):
                    self.storage_store(key, v, created, node.addr)
        return RequestAnswer()

    def _on_refresh(self, node: Node, key: InfoHash, token: bytes,
                    vid: int) -> RequestAnswer:
        """(↔ Dht::onRefresh, src/dht.cpp:2341-2360)"""
        if not self._token_match(token, node.addr):
            raise DhtProtocolException(DhtProtocolException.UNAUTHORIZED,
                                       DhtProtocolException.PUT_WRONG_TOKEN)
        st = self.store.get(key)
        new_exp = (st.refresh(self.scheduler.time(), vid, key)
                   if st is not None else None)
        if new_exp is None:
            raise DhtProtocolException(DhtProtocolException.NOT_FOUND,
                                       DhtProtocolException.STORAGE_NOT_FOUND)
        # the sweep scheduled at the original expiration will now keep the
        # value; cover the extended lifetime with a new sweep
        self._calendar_add(key, new_exp)
        return RequestAnswer()

    # ============================================================ maintenance
    def _confirm_nodes(self) -> None:
        """(↔ Dht::confirmNodes, src/dht.cpp:1929-1965)"""
        now = self.scheduler.time()
        soon = False
        for af in self.tables:
            if not self.searches[af] and \
                    self.get_status(af) is NodeStatus.CONNECTED:
                self._search(self.myid, af)
            soon |= self._bucket_maintenance(af)
        if not soon:
            for af in self.tables:
                if self._table_grow_time[af] >= now - 150:
                    soon |= self._neighbourhood_maintenance(af)
        lo, hi = (5, 25) if soon else (60, 180)
        self._next_nodes_confirmation = self.scheduler.edit(
            self._next_nodes_confirmation, now + random.uniform(lo, hi))
        for af in self.tables:
            self._update_status(af)

    def _random_node_near(self, af: int, target: InfoHash) -> Optional[Node]:
        nodes = self.find_closest_nodes(target, af, TARGET_NODES)
        return random.choice(nodes) if nodes else None

    def _bucket_maintenance(self, af: int) -> bool:
        """Random find in stale buckets (↔ Dht::bucketMaintenance,
        src/dht.cpp:1780-1838) — round 10: occupancy, staleness AND the
        refresh targets come from ONE fused device pass
        (ops/radix.maintenance_sweep, threading the table's reusable
        PRNG key), and the per-target node picks come from ONE batched
        closest-node resolve instead of a single-target launch (and its
        full 128-lane padding tax) per stale bucket."""
        table = self.tables[af]
        now = self.scheduler.time()
        if len(table) == 0:
            return False
        reg = telemetry.get_registry()
        with reg.span("dht_maintenance_sweep_seconds"):
            stale, targets = table.maintenance_sweep(now)
        self._m_maint_sweeps.inc()
        # publish the stale-bucket fraction + occupancy per family
        # (round 14): the health evaluator's ``stale_buckets`` signal
        # reads these gauges instead of launching its own sweep — the
        # fused pass already computed occupancy AND staleness, so
        # health costs no kernel.  Occupancy rides along because the
        # fraction is only statistically meaningful on tables with
        # enough occupied buckets (a 3-node table's 1-2 buckets swing
        # the fraction 0→1 on one never-replied peer).
        # keyed by node AND family: co-resident nodes in one process
        # share the registry (documented round-8 semantics), and a
        # node-less key would let node A's sweep overwrite the signal
        # node B's health evaluator reads (review finding)
        fam = "ipv4" if af == _socket.AF_INET else "ipv6"
        nid = str(self.myid)
        occupied = int(np.count_nonzero(table.bucket_occupancy()))
        reg.gauge("dht_maintenance_stale_fraction", family=fam,
                  node=nid).set(len(stale) / occupied if occupied else 0.0)
        reg.gauge("dht_maintenance_occupied_buckets", family=fam,
                  node=nid).set(occupied)
        if len(stale) == 0:
            return False
        raw = IK.ids_to_bytes(targets)
        tids = [InfoHash(raw[i].tobytes()) for i in range(targets.shape[0])]
        near = self.find_closest_nodes_batched(tids, af, TARGET_NODES)
        sent = False
        for tid, nodes in zip(tids, near):
            n = random.choice(nodes) if nodes else None
            if n is not None and not n.is_pending():
                def on_expired(req, over, _n=n):
                    if over:
                        self._next_nodes_confirmation = self.scheduler.edit(
                            self._next_nodes_confirmation,
                            self.scheduler.time() + MAX_RESPONSE_TIME)
                self.engine.send_find_node(n, tid, self._want(),
                                           None, on_expired)
                sent = True
                self._m_maint_refresh.inc()
        tr = tracing.get_tracer()
        if tr.enabled:
            tr.event("bucket_refresh", af=af, stale=int(len(stale)),
                     sent=sent)
        return sent

    def _neighbourhood_maintenance(self, af: int) -> bool:
        """Find near own id (↔ Dht::neighbourhoodMaintenance,
        src/dht.cpp:1742-1778)."""
        nid = InfoHash(bytes(self.myid)[:-1] + bytes([random.getrandbits(8)]))
        n = self._random_node_near(af, nid)
        if n is None:
            return False
        self.engine.send_find_node(n, nid, self._want(), None, None)
        return True

    def _expire_sweep(self) -> None:
        """(↔ Dht::expire, src/dht.cpp:1916-1927)"""
        now = self.scheduler.time()
        for af, table in self.tables.items():
            table.clear_bad()
        self._expire_store_all()
        self._expire_searches()
        self.scheduler.add(now + random.uniform(2 * 60, 6 * 60),
                           self._expire_sweep)

    def _expire_searches(self) -> None:
        """(↔ Dht::expireSearches, src/dht.cpp:195-210)"""
        t = self.scheduler.time() - SEARCH_EXPIRE_TIME
        for af, srs in self.searches.items():
            dead = [key for key, sr in srs.items()
                    if not sr.callbacks and not sr.announce
                    and not sr.listeners and sr.step_time < t]
            for key in dead:
                sr = srs.pop(key)
                sr.clear()
                self._search_keys[af].remove(bytes(key))

    def connectivity_changed(self, af: int = 0) -> None:
        """Reset liveness state after a network change
        (↔ Dht::connectivityChanged, src/dht.cpp:1351-1367)."""
        fams = [af] if af else list(self.tables)
        self._next_nodes_confirmation = self.scheduler.edit(
            self._next_nodes_confirmation, self.scheduler.time())
        for fam in fams:
            if fam not in self.tables:
                continue
            self.engine.connectivity_changed(fam)
            for sr in self.searches[fam].values():
                for sn in sr.nodes:
                    sn.cancel_listen()
            self.reported_addr = [
                (c, a) for c, a in self.reported_addr if a.family != fam]

    # ================================================================ node ops
    def insert_node(self, node_id: InfoHash, addr: SockAddr) -> None:
        """Seed a known peer without pinging (↔ Dht::insertNode,
        src/dht.cpp:2060-2067)."""
        if addr.family not in (_socket.AF_INET, _socket.AF_INET6):
            return
        self.scheduler.sync_time()
        now = self.scheduler.time()
        n = self.engine.cache.get_node(node_id, addr, now, confirm=False)
        self._on_new_node(n, 0)

    def ping_node(self, addr: SockAddr, done_cb=None) -> None:
        """(↔ Dht::pingNode, src/dht.cpp:2069-2087)"""
        self.scheduler.sync_time()
        af = addr.family
        if af in self._pending_pings:
            self._pending_pings[af] += 1
        node = self.engine.cache.get_node(InfoHash(), addr,
                                          self.scheduler.time(),
                                          confirm=False)

        def on_done(req, answer):
            if af in self._pending_pings:
                self._pending_pings[af] -= 1
            self._update_status(af)
            if done_cb:
                done_cb(True)

        def on_expired(req, over):
            if over:
                if af in self._pending_pings:
                    self._pending_pings[af] -= 1
                if done_cb:
                    done_cb(False)

        self.engine.send_ping(node, on_done, on_expired)

    # ================================================================== status
    def get_nodes_stats(self, af: int) -> NodeStats:
        """(↔ Dht::getNodesStats, src/dht.cpp:1424-1444)"""
        stats = NodeStats()
        table = self._table(af)
        if table is None:
            return stats
        now = self.scheduler.time()
        good = table.good_mask(now)
        reach = table.reachable_mask(now)
        stats.good_nodes = int(np.count_nonzero(good))
        stats.dubious_nodes = int(np.count_nonzero(reach & ~good))
        stats.cached_nodes = len(table._cached)
        incoming = good & (table._time_seen > table._time_reply)
        stats.incoming_nodes = int(np.count_nonzero(incoming))
        occ = table.bucket_occupancy()
        nz = np.nonzero(occ)[0]
        stats.table_depth = int(nz[-1] + 1) if len(nz) else 0
        stats.searches = len(self._searches_of(af))
        stats.node_cache_size = self.engine.cache.size(af)
        return stats

    def get_status(self, af: int = 0) -> NodeStatus:
        """(↔ Dht::getStatus, dht.h:209-218)"""
        if af == 0:
            return max((self.get_status(a) for a in self.tables),
                       key=lambda s: s.value, default=NodeStatus.DISCONNECTED)
        stats = self.get_nodes_stats(af)
        if stats.good_nodes:
            return NodeStatus.CONNECTED
        if self._pending_pings.get(af, 0) or stats.get_known_nodes():
            return NodeStatus.CONNECTING
        return NodeStatus.DISCONNECTED

    def _update_status(self, af: int, *, debounce: bool = False) -> None:
        """Re-evaluate the node status and fire status_cb on change.

        ``debounce=True`` (the per-packet on_new_node path) rates the
        O(table) ``get_nodes_stats`` sweep at once per second of node
        time, rescheduling itself for the window's end so a transition
        is delayed ≤ 1 s, never lost.  Un-debounced, the sweep ran once
        per confirmed node event and was the top profile entry of big
        virtual clusters (381K calls over an 84 s 1024-node run)."""
        now = self.scheduler.time()
        if debounce:
            last = self._status_checked.get(af, float("-inf"))
            if now - last < 1.0:
                if not self._status_recheck.get(af):
                    self._status_recheck[af] = self.scheduler.add(
                        last + 1.0, lambda: self._status_tick(af))
                return
            self._status_checked[af] = now
        st = self.get_status(af)
        if st is not self._last_status.get(af):
            self._last_status[af] = st
            if self.status_cb:
                self.status_cb(
                    self._last_status.get(_socket.AF_INET,
                                          NodeStatus.DISCONNECTED),
                    self._last_status.get(_socket.AF_INET6,
                                          NodeStatus.DISCONNECTED))

    def _status_tick(self, af: int) -> None:
        """The scheduled end-of-window re-evaluation: ALWAYS does the
        full check.  It must not re-enter the window logic — float
        rounding can make ``(last + 1.0) - last < 1.0``, and the
        re-entered window branch would then re-schedule the job at its
        own (already due) fire time: an infinite self-rescheduling loop
        at a frozen virtual clock (measured: 5M ticks in 0.5 virtual
        seconds before this fix)."""
        self._status_recheck.pop(af, None)
        self._status_checked[af] = self.scheduler.time()
        self._update_status(af)

    def network_size_estimate(self, af: int = _socket.AF_INET) -> int:
        table = self._table(af)
        return table.network_size_estimate() if table is not None else 0

    # ======================================================== persist / import
    def export_nodes(self) -> List[dict]:
        """Good nodes for bootstrap persistence (↔ Dht::exportNodes,
        src/dht.cpp:2029-2059)."""
        out = []
        now = self.scheduler.time()
        for table in self.tables.values():
            for node_id, addr in table.export_nodes(now):
                out.append({"id": bytes(node_id), "addr": addr.to_compact()
                            if hasattr(addr, "to_compact") else addr})
        return out

    def export_values(self) -> List[tuple]:
        """(↔ Dht::exportValues, src/dht.cpp:1967-1990)"""
        out = []
        for key, st in self.store.items():
            vals = [(int(vs.created + _wall_offset()), vs.data.get_packed())
                    for vs in st.values]
            out.append((bytes(key), vals))
        return out

    def import_values(self, exported: List[tuple]) -> None:
        """(↔ Dht::importValues, src/dht.cpp:1992-2026)"""
        now = self.scheduler.time()
        for entry in exported:
            # one malformed entry must not abort the rest of the import
            try:
                key_raw, vals = entry
                key = InfoHash(key_raw)
            except Exception:
                log.exception("skipping malformed import entry")
                continue
            for item in vals:
                try:
                    created_wall, packed = item
                    v = Value.from_packed(packed)
                except Exception:
                    log.exception("failed to import value for %s", key,
                                  extra={"dht_hash": bytes(key)})
                    continue
                created = min(now, created_wall - _wall_offset())
                self.storage_store(key, v, created)

    # =============================================================== log dumps
    def get_storage_log(self) -> str:
        """(↔ Dht::getStorageLog, src/dht.cpp:1596-1612)"""
        lines = []
        for key, st in self.store.items():
            listeners = sum(len(m) for m in st.listeners.values())
            lines.append(f"Storage {key} {listeners} list. "
                         f"{st.value_count()} values ({st.total_size} bytes)")
        lines.append(f"Total {self.total_values} values, "
                     f"{self.total_store_size // 1024} KB "
                     f"({self.max_store_size // 1024} KB max)")
        return "\n".join(lines)

    def get_routing_tables_log(self, af: int) -> str:
        table = self._table(af)
        if table is None:
            return ""
        occ = table.bucket_occupancy()
        lines = [f"Routing table (IPv{'4' if af == _socket.AF_INET else '6'}) "
                 f"{len(table)} nodes"]
        for b in np.nonzero(occ)[0]:
            lines.append(f"  bucket {int(b):3d}: {int(occ[b])} nodes")
        return "\n".join(lines)

    def get_searches_log(self, af: int = 0) -> str:
        lines = []
        for fam, srs in self.searches.items():
            if af and fam != af:
                continue
            for key, sr in srs.items():
                lines.append(
                    f"Search {key} IPv{'4' if fam == _socket.AF_INET else '6'}"
                    f" nodes={len(sr.nodes)} done={sr.done} "
                    f"synced={sr.is_synced(self.scheduler.time())} "
                    f"gets={len(sr.callbacks)} puts={len(sr.announce)} "
                    f"listeners={len(sr.listeners)}")
        return "\n".join(lines)

    # ================================================================== types
    def register_type(self, vt) -> None:
        self.types.register_type(vt)

    def get_type(self, type_id: int):
        return self.types.get_type(type_id)

    def set_storage_limit(self, limit: int) -> None:
        self.max_store_size = limit

    def get_node_id(self) -> InfoHash:
        return self.myid

    def shutdown(self, cb=None) -> None:
        """Flush permanent puts and stop (simplified: the reference also
        re-announces permanent values once, dhtrunner.cpp:217-248)."""
        for srs in self.searches.values():
            for sr in srs.values():
                sr.stop()
        if cb:
            cb()


def _wall_offset() -> float:
    """monotonic→wall clock offset for export/import timestamps."""
    import time
    return wall_now() - time.monotonic()
