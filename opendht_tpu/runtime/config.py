"""Node status, stats and configuration (reference
include/opendht/callbacks.h:41-117)."""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Optional

# the declarative SLO/health config (round 14) lives in
# opendht_tpu/health.py (import-light, stdlib + telemetry spine) and is
# re-exported here because runtime/config.py is where node behavior is
# configured — `Config.health` is the knob surface
from ..health import HealthConfig, SloObjective, default_slos  # noqa: F401
from ..history import HistoryConfig  # noqa: F401  (same knob-surface rule)
from ..keyspace import KeyspaceConfig  # noqa: F401  (same knob-surface rule)
from ..hotcache import HotCacheConfig  # noqa: F401  (same knob-surface rule)
from ..waterfall import WaterfallConfig  # noqa: F401  (same knob-surface rule)
from ..reshard import ReshardConfig  # noqa: F401  (same knob-surface rule)
from ..pipeline_observatory import PipelineObservatoryConfig  # noqa: F401,E501  (same knob-surface rule)
from ..peers import PeersConfig  # noqa: F401  (same knob-surface rule)
from ..listeners import ListenerTableConfig  # noqa: F401  (same knob-surface rule)
from ..infohash import InfoHash

#: total value-store budget per node (callbacks.h:117)
DEFAULT_STORAGE_LIMIT = 64 * 1024 * 1024


class NodeStatus(enum.Enum):
    """(callbacks.h:41-45)"""
    DISCONNECTED = 0     # 0 nodes
    CONNECTING = 1       # 1+ nodes known, no confirmed peer yet
    CONNECTED = 2        # 1+ good nodes


@dataclass
class NodeStats:
    """Routing-table health counters (callbacks.h:47-67)."""
    good_nodes: int = 0
    dubious_nodes: int = 0
    cached_nodes: int = 0
    incoming_nodes: int = 0
    table_depth: int = 0
    searches: int = 0
    node_cache_size: int = 0

    def get_known_nodes(self) -> int:
        return self.good_nodes + self.dubious_nodes

    def get_network_size_estimation(self) -> int:
        """8 · 2^depth (callbacks.h:54)."""
        return 8 * (2 ** self.table_depth)

    def to_dict(self) -> dict:
        return {
            "good": self.good_nodes, "dubious": self.dubious_nodes,
            "cached": self.cached_nodes, "incoming": self.incoming_nodes,
            "searches": self.searches, "node_cache": self.node_cache_size,
            "table_depth": self.table_depth,
            "network_size_estimation": self.get_network_size_estimation(),
        }


@dataclass
class Config:
    """DHT node configuration (callbacks.h:90-106)."""
    node_id: Optional[InfoHash] = None
    network: int = 0                 # netid partitioning the DHT
    is_bootstrap: bool = False       # client mode: don't join tables
    maintain_storage: bool = False   # republish values toward closer nodes
    storage_limit: int = DEFAULT_STORAGE_LIMIT
    max_req_per_sec: int = 1600      # ingress budget; per-IP = this // 8

    # --- continuous-batching ingest (round 12, runtime/wave_builder.py) ---
    #: "on" coalesces live search refills into shared [Q] device
    #: launches; "off" is the escape hatch pinned result-equivalent to
    #: the per-op dispatch path (one padded launch per op)
    ingest_batching: str = "on"
    #: fill target Q: a wave fires as soon as this many lookups queue
    ingest_fill_target: int = 64
    #: deadline knob (seconds): the oldest queued lookup's maximum wait
    #: before a partial wave fires anyway
    ingest_deadline: float = 0.002
    #: admission bound: NEW ops are shed (never in-flight searches)
    #: once this many lookups are queued
    ingest_queue_max: int = 4096
    #: optional op-admission quota (ops/s through rate_limiter.
    #: RateLimiter, the same sliding window the net engine's ingress
    #: quotas use); 0 = unlimited
    ingest_admit_per_sec: int = 0
    #: pipeline depth (round 20): how many ingest waves may be in
    #: flight on device at once.  2 (the default double-buffer) fills
    #: wave N+1 and drains wave N−1's scatter while wave N runs on
    #: device; 1 = exact pre-pipeline behavior (launch→block→scatter
    #: inline, the escape hatch — pinned result-equivalent in
    #: tests/test_wave_builder.py).  Validated ≥ 1 by WaveBuilder.
    ingest_pipeline_depth: int = 2

    # --- t-sharded resolve (round 13, parallel/partition.py) ----------
    #: row-shard the device-side closest-node resolve over a t-wide
    #: mesh axis: ingest waves (and any other big-batch find_closest)
    #: run the per-shard windowed top-k + one cross-shard merge instead
    #: of the single-device kernel, so the servable table scales past
    #: one chip's HBM.  0/1 = unsharded (the default single-device
    #: path); >= 2 requires that many jax devices (falls back to
    #: unsharded with a logged warning when the host has fewer).
    #: Results are bit-identical either way (tests/test_sharded.py).
    resolve_mesh_t: int = 0

    # --- health observatory (round 14, opendht_tpu/health.py) ---------
    #: declarative SLO engine + per-node health verdict: per-op
    #: availability/latency objectives with multi-window burn-rate
    #: evaluation, derived signals (ingest queue saturation, scheduler
    #: tick lag, request timeout ratio, stale buckets, connectivity),
    #: evaluated every ``health.period`` seconds on the node scheduler
    #: and exported as `dht_health_*`/`dht_slo_*` gauges, flight
    #: events, and the proxy's readiness route ``GET /healthz``.
    #: ``health.period = 0`` disables the tick entirely.
    health: HealthConfig = field(default_factory=HealthConfig)

    # --- flight data recorder (round 17, opendht_tpu/history.py) ------
    #: bounded ring of periodic delta-encoded registry frames (counters
    #: as deltas, histograms as bucket deltas, gauges as last-value)
    #: ticking on the node scheduler, with windowed ``rate``/
    #: ``quantile`` queries, optional bounded on-disk spill
    #: (``history.spill_dir``), and post-mortem black-box bundles —
    #: auto-captured on every health transition to unhealthy, served
    #: fresh by ``DhtRunner.dump_bundle()`` / proxy ``GET
    #: /debug/bundle`` / the ``bundle`` REPL cmd / ``dhtscanner
    #: --bundle DIR``.  When the recorder is live, the health engine's
    #: windowed SLO deltas read THROUGH its frames (one delta
    #: codepath) and ``dhtmon --window/--since`` query ``GET
    #: /history`` instead of scrape-diff-scrape.  ``history.period =
    #: 0`` disables the recorder (surfaces report ``enabled: false``;
    #: the health engine falls back to its private windows).
    history: HistoryConfig = field(default_factory=HistoryConfig)

    # --- keyspace traffic observatory (round 15, opendht_tpu/keyspace.py) --
    #: device-resident count-min sketch + 256-bin keyspace histogram
    #: over the ingest waves' target ids (one batched scatter-add per
    #: wave) and stored-key puts: periodic heavy-hitter top-K with
    #: ``hot_key_emerged`` flight events, exponential-decay windowing,
    #: and per-shard load attribution feeding the ``shard_imbalance``
    #: health signal, `dht_keyspace_*`/`dht_hotkey_*`/
    #: `dht_shard_imbalance` gauges, proxy ``GET /keyspace``, the
    #: `keyspace` REPL cmd and `dhtmon --max-imbalance`.
    #: ``keyspace.enabled = False`` turns every launch and surface off
    #: (results are identical either way — the sketch only observes).
    keyspace: KeyspaceConfig = field(default_factory=KeyspaceConfig)

    # --- hot-key serving cache (round 16, opendht_tpu/hotcache.py) ----
    #: the acting half of the observe→act loop: a bounded device table
    #: of the observatory's hot keys (canonical 20-byte ids) + host
    #: value payloads, probed in ONE batched XOR-compare launch before
    #: every ingest wave so hot gets are served from cache without
    #: joining the ``[Q]`` lookup launch, invalidated on observed puts
    #: (a put is visible on the next get, never a stale hit), plus
    #: adaptive replica widening (closest-8 → closest-16 while a key is
    #: hot, narrowing on decay).  Surfaces: ``dht_cache_*`` series +
    #: hit ratio on ``GET /stats``/``get_metrics()``, proxy
    #: ``GET /cache``, the ``cache`` REPL cmd, ``dhtmon
    #: --min-cache-hit`` and a degrade-only ``cache_hit_ratio`` health
    #: signal.  ``cache.enabled = False`` turns the probe, fast path
    #: and widening off — results are pinned identical either way.
    cache: HotCacheConfig = field(default_factory=HotCacheConfig)

    # --- adversarial chaos plane (round 18, opendht_tpu/chaos.py) -----
    #: allow a FaultPlan to be armed on this node's live engine send
    #: path (``chaos.arm_dht``).  Off by default: with no plan armed
    #: the engine's fault hook is None and the send path is
    #: byte-identical to pre-chaos builds (pinned in
    #: tests/test_chaos.py).  Test harnesses that own their nodes
    #: (testing/network.py, testing/virtual_net.py) arm with
    #: ``force=True`` instead of flipping this.
    chaos_enabled: bool = False

    # --- per-op latency waterfall (round 19, opendht_tpu/waterfall.py) --
    #: always-on stage profiler over the full serving path:
    #: ``dht_stage_seconds{stage=}`` histograms (queue_wait /
    #: cache_probe / device_compile / device_launch / scatter_back /
    #: rpc_wait) with exemplar trace ids on the hot buckets, a bounded
    #: per-op decomposition ring, the degrade-only ``stage_budget``
    #: health signal, and the live OPEN-bound tracker
    #: (``dht_open_bound{key=,status=}`` gauges + settling records into
    #: ``$OPENDHT_TPU_SMOKE_RECORD_DIR``).  Surfaces: ``GET /profile``
    #: (+ ``?fmt=folded``), the ``profile`` REPL cmd, the scanner's
    #: ``waterfall`` section and ``dhtmon --max-stage``.
    #: ``waterfall.enabled = False`` stops observation entirely —
    #: results are identical either way (the profiler only observes).
    waterfall: WaterfallConfig = field(default_factory=WaterfallConfig)

    # --- load-aware resharding (round 21, opendht_tpu/reshard.py) -----
    #: the rebalance tick closing the observe→act loop on
    #: ``dht_shard_imbalance``: when the windowed imbalance stays above
    #: ``reshard.rebalance_threshold`` for ``reshard.sustain`` seconds
    #: (hysteresis latch + history-frame corroboration; min-interval
    #: cooldown), new traffic-weighted shard boundaries are solved from
    #: the observatory's load histogram (blended with row counts by
    #: ``rebalance_load_weight``) and hot-swapped under the serving
    #: path between waves.  Lookup results are pinned bit-identical to
    #: the single-device engine before, during and after a swap
    #: (tests/test_reshard.py).  Surfaces: ``dht_reshard_*`` series,
    #: `reshard_swap` flight events + trace spans, proxy
    #: ``GET /reshard``, the ``reshard`` REPL cmd and the scanner
    #: section.  ``reshard.period = 0`` (or ``enabled = False``)
    #: disables the tick — the layout then never moves off uniform.
    reshard: ReshardConfig = field(default_factory=ReshardConfig)

    # --- pipeline observatory (round 22, pipeline_observatory.py) -----
    #: concurrency-aware utilization plane over the async wave
    #: pipeline: per-wave lane timelines (fill / device / drain), the
    #: windowed ``dht_pipeline_occupancy`` device-occupancy gauge,
    #: per-cause ``dht_pipeline_bubble_seconds{cause=}`` device-idle
    #: attribution (+ top-cause gauge), measured fill∥device overlap
    #: (``dht_pipeline_overlap_ratio``) and a Perfetto lane export.
    #: Surfaces: ``GET /pipeline`` (+ ``?fmt=trace``), the ``pipeline``
    #: REPL cmd, the scanner's ``pipeline`` section, ``dhtmon
    #: --min-occupancy`` and the degrade-only ``pipeline_occupancy``
    #: health signal.  Host-side edge bookkeeping only — kernels and
    #: results are bit-identical with the plane on
    #: (tests/test_pipeline_observatory.py).  ``pipeline.enabled =
    #: False`` turns every hook into an early return.
    pipeline: PipelineObservatoryConfig = field(
        default_factory=PipelineObservatoryConfig)

    # --- per-peer network observatory (round 23, opendht_tpu/peers.py) --
    #: bounded LRU ledger over remote peers fed from the request
    #: lifecycle: Jacobson/Karels RTT EWMA + variance per peer,
    #: per-peer sent/completed/timeout/cancel counts, bytes in/out by
    #: message type and good<->dubious<->expired flap transitions
    #: mirroring the reference's ``net::Node`` liveness rules.
    #: ``peers.adaptive_rto`` (off by default) closes the loop into
    #: the retransmit timer: per-attempt timeout = srtt + 4*rttvar
    #: clamped to [rto_min, rto_max], pinned exactly
    #: ``MAX_RESPONSE_TIME`` while a peer has no RTT samples.
    #: Surfaces: ``dht_peer_*`` series, proxy ``GET /peers``, the
    #: ``peers`` REPL cmd, the scanner's ``peers`` section, ``dhtmon
    #: --max-peer-fail``, the degrade-only ``peer_flap`` health signal
    #: and the testing/wiremap_assembler.py cluster wire map.
    #: ``peers.enabled = False`` removes every hook — the request
    #: lifecycle is then byte- and timing-identical to pre-round-23
    #: builds (the ledger only observes; wire bytes are pinned
    #: bit-identical either way in benchmarks/exp_peers_r23.py).
    peers: PeersConfig = field(default_factory=PeersConfig)

    # --- wave-scale listen/push (round 24, opendht_tpu/listeners.py) --
    #: "on" defers each stored put's listener notification into a
    #: bounded buffer answered by ONE batched XOR-equality launch per
    #: ingest wave (``ops/listener_match.py``) and dispatches one
    #: coalesced callback/``tell_listener``/proxy push per wave per
    #: listener; "off" is the escape hatch — the exact synchronous
    #: per-put probe path, pinned result-equivalent (same values, same
    #: per-listener order) in tests/test_listener.py and
    #: testing/listener_smoke.py.
    listen_batching: str = "on"
    #: the device-resident listener table behind the launch: bounded
    #: ``[L, 5]`` key-id slots (tombstoned/compacted on cancel/expiry,
    #: host overflow past capacity), ``entry_ttl`` re-check sweep,
    #: ``flush_deadline`` so idle nodes still deliver promptly.
    #: Surfaces: ``dht_listener_*`` series on ``get_metrics()``/
    #: ``GET /stats``/the history ring, proxy ``GET /listeners``, the
    #: ``listeners`` REPL cmd, the scanner section and ``dhtmon
    #: --max-listener-lag``.  Device failure goes dark to the
    #: synchronous path (a delivery can be late, never lost).
    listeners: ListenerTableConfig = field(
        default_factory=ListenerTableConfig)


@dataclass
class SecureDhtConfig:
    """(callbacks.h:111-115); identity = (PrivateKey, Certificate)."""
    node_config: Config = field(default_factory=Config)
    identity: Optional[tuple] = None
