"""SecureDht: crypto overlay over any Dht-like backend.

Behavioral port of the reference SecureDht (reference:
include/opendht/securedht.h:33-371, src/securedht.cpp):

- wraps a ``Dht`` (or any object with the same get/put/listen surface) and
  an :class:`~opendht_tpu.crypto.Identity`;
- ``secure_type`` injects signature checks into store policies and
  owner+seq rules into edit policies (securedht.cpp:67-105);
- ``check_value`` verifies signed values and decrypts encrypted values
  addressed to us, caching sender public keys (securedht.cpp:226-264);
- ``get``/``listen`` wrap user callbacks with that filter
  (securedht.cpp:266-316);
- ``put_signed`` bumps seq past both local announces and network state
  then signs (securedht.cpp:318-354); ``put_encrypted`` resolves the
  recipient key then sign+encrypt (securedht.cpp:356-374);
- our certificate is published as a permanent CERTIFICATE_TYPE value at
  the public-key id (securedht.cpp:48-61);
- node id for the underlying Dht = H("node:" + cert-id-hex)
  (securedht.h:40-46).
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional

from ..infohash import InfoHash
from ..core.default_types import DEFAULT_INSECURE_TYPES, DEFAULT_TYPES
from ..core.value import Filters, Value, ValueType, random_value_id
from ..utils import lazy_module, unpack_msg
from .config import Config, SecureDhtConfig

# call-time dependency only: every crypto touch happens per-value or
# per-identity, so the module imports (and an identity-less SecureDht
# runs) without the `cryptography` wheel — certificate policies then
# reject stores via their existing except-paths instead of crashing
crypto = lazy_module("opendht_tpu.crypto")

log = logging.getLogger("opendht_tpu.secure")

WEEK = 7 * 24 * 3600.0


def _certificate_store_policy(key, value, from_id, from_addr) -> bool:
    """A certificate can only be stored at its public-key id
    (securedht.h:352-361)."""
    try:
        return crypto.Certificate(value.data).get_id() == key
    except Exception:
        return False


def _certificate_edit_policy(key, old, new, from_id, from_addr) -> bool:
    """(securedht.h:362-369)"""
    try:
        return (crypto.Certificate(old.data).get_id()
                == crypto.Certificate(new.data).get_id())
    except Exception:
        return False


CERTIFICATE_TYPE = ValueType(8, "Certificate", WEEK,
                             _certificate_store_policy,
                             _certificate_edit_policy)


def secure_node_id(cert: crypto.Certificate) -> InfoHash:
    """Dht node id derived from the certificate (securedht.h:40-46)."""
    return InfoHash.get("node:" + str(cert.get_id()))


def secure_config(conf: SecureDhtConfig) -> Config:
    """SecureDht::getConfig: fill node_id from the identity."""
    c = conf.node_config
    if not c.node_id and conf.identity and conf.identity[1] is not None:
        c.node_id = secure_node_id(conf.identity[1])
    return c


class SecureDht:
    """Crypto wrapper; forwards the full DhtInterface surface to the inner
    Dht and layers signature/encryption semantics on top."""

    def __init__(self, dht, identity: "crypto.Identity | tuple | None" = None):
        self._dht = dht
        key, cert = (identity if identity else (None, None))
        self.key: Optional[crypto.PrivateKey] = key
        self.certificate: Optional[crypto.Certificate] = cert
        #: InfoHash → Certificate
        self.node_certificates: Dict[InfoHash, crypto.Certificate] = {}
        #: InfoHash → PublicKey
        self.node_pubkeys: Dict[InfoHash, object] = {}
        #: optional local certificate store query (securedht.h:309-311)
        self.local_query_method: Optional[Callable] = None
        #: proxy-server mode: forward encrypted values unopened
        self.forward_all = False

        for vt in DEFAULT_TYPES:
            self.register_type(vt)
        for vt in DEFAULT_INSECURE_TYPES:
            self.register_insecure_type(vt)
        self.register_insecure_type(CERTIFICATE_TYPE)

        if cert is not None:
            cert_id = cert.get_id()
            if key is not None and cert_id != key.public_key().get_id():
                raise crypto.CryptoException(
                    "SecureDht: provided certificate doesn't match private key")
            v = Value(cert.pack())
            v.type = CERTIFICATE_TYPE.id
            v.id = 1
            self._dht.put(cert_id, v,
                          lambda ok, ns: ok and log.debug(
                              "public key announced successfully"),
                          permanent=True)

    # ------------------------------------------------------------- identity
    def get_id(self) -> InfoHash:
        """Our crypto-layer id = public key fingerprint (securedht.h:60-62)."""
        return (self.key.public_key().get_id() if self.key is not None
                else InfoHash())

    def get_long_id(self):
        return (self.key.public_key().get_long_id() if self.key is not None
                else None)

    # ---------------------------------------------------------------- types
    def secure_type(self, vt: ValueType) -> ValueType:
        """Wrap policies with signature enforcement (securedht.cpp:67-105)."""
        base_store, base_edit = vt.store_policy, vt.edit_policy

        def store_policy(key, v, nid, addr):
            if v.is_signed():
                # wire values carry an unparsed RawPublicKey owner; upgrade
                # it so the signature can actually be checked
                self._parse_owner(v)
                if v.owner is None or not v.check_signature():
                    log.warning("signature verification failed for %s", key,
                                extra={"dht_hash": bytes(key)})
                    return False
            return base_store(key, v, nid, addr)

        def edit_policy(key, o, n, nid, addr):
            if not o.is_signed():
                return base_edit(key, o, n, nid, addr)
            self._parse_owner(o)
            self._parse_owner(n)
            if o.owner is None or n.owner is None \
                    or o.owner.export_der() != n.owner.export_der():
                log.warning("edition forbidden: owner changed",
                            extra={"dht_hash": bytes(key)})
                return False
            if not o.owner.check_signature(n.get_to_sign(), n.signature):
                log.warning("edition forbidden: signature verification failed",
                            extra={"dht_hash": bytes(key)})
                return False
            if o.seq == n.seq:
                # identical data may be re-announced, possibly by others
                return o.get_to_sign() == n.get_to_sign()
            return n.seq > o.seq

        return ValueType(vt.id, vt.name, vt.expiration,
                         store_policy, edit_policy)

    def register_type(self, vt: ValueType) -> None:
        self._dht.register_type(self.secure_type(vt))

    def register_insecure_type(self, vt: ValueType) -> None:
        self._dht.register_type(vt)

    # ----------------------------------------------------- certificate ops
    def get_certificate(self, node: InfoHash):
        if node == self.get_id():
            return self.certificate
        return self.node_certificates.get(node)

    def get_public_key(self, node: InfoHash):
        if node == self.get_id() and self.key is not None:
            return self.key.public_key()
        return self.node_pubkeys.get(node)

    def register_certificate(self, cert_or_node, data: Optional[bytes] = None):
        """Cache a certificate; with (node, blob) form, check the id
        matches (securedht.cpp:131-160)."""
        if data is None:
            cert = cert_or_node
            if cert is not None:
                self.node_certificates[cert.get_id()] = cert
            return cert
        try:
            crt = crypto.Certificate(data)
        except Exception:
            return None
        if crt.get_id() != cert_or_node:
            log.debug("certificate %s does not match node id %s",
                      crt.get_id(), cert_or_node,
                      extra={"dht_hash": bytes(InfoHash(cert_or_node))})
            return None
        self.node_certificates[crt.get_id()] = crt
        return crt

    def find_certificate(self, node: InfoHash, cb) -> None:
        """Cache → local store → DHT get (securedht.cpp:163-203)."""
        cached = self.get_certificate(node)
        if cached is not None:
            if cb:
                cb(cached)
            return
        if self.local_query_method is not None:
            res = self.local_query_method(node)
            if res:
                self.node_certificates[node] = res[0]
                if cb:
                    cb(res[0])
                return
        state = {"found": False}

        def get_cb(values: List[Value]) -> bool:
            if state["found"]:
                return False
            for v in values:
                cert = self.register_certificate(node, v.data)
                if cert is not None:
                    state["found"] = True
                    if cb:
                        cb(cert)
                    return False
            return True

        def done_cb(ok, nodes):
            if not state["found"] and cb:
                cb(None)

        self._dht.get(node, get_cb, done_cb,
                      Filters.type_filter(CERTIFICATE_TYPE))

    def find_public_key(self, node: InfoHash, cb) -> None:
        """(securedht.cpp:205-224)"""
        pk = self.get_public_key(node)
        if pk is not None:
            if cb:
                cb(pk)
            return

        def on_cert(cert):
            if cert is not None:
                pk = cert.get_public_key()
                self.node_pubkeys[pk.get_id()] = pk
                if cb:
                    cb(pk)
                return
            if cb:
                cb(None)

        self.find_certificate(node, on_cert)

    # ------------------------------------------------------ value checking
    def check_value(self, v: Value) -> Optional[Value]:
        """Verify/decrypt one incoming value (securedht.cpp:226-264).
        Returns the value to surface, or None to drop it."""
        if v.is_encrypted():
            if self.key is None:
                return v if self.forward_all else None
            try:
                dv = self.decrypt(v)
            except Exception as e:
                log.warning("could not decrypt value %s: %s", v.id, e)
                return None
            if dv.owner is not None:
                self.node_pubkeys[dv.owner.get_id()] = dv.owner
            return dv
        if v.is_signed():
            v = self._parse_owner(v)
            if v.owner is not None and v.check_signature():
                self.node_pubkeys[v.owner.get_id()] = v.owner
                return v
            log.warning("signature verification failed for value %s", v.id)
            return None
        return v

    @staticmethod
    def _parse_owner(v: Value) -> Value:
        """Upgrade a wire RawPublicKey owner to a real PublicKey so the
        signature can actually be verified."""
        if v.owner is not None and not isinstance(v.owner, crypto.PublicKey):
            try:
                v.owner = crypto.PublicKey(v.owner.export_der())
            except Exception:
                pass
        return v

    def _filtered_get_cb(self, cb, f=None):
        """(securedht.cpp:286-303)"""
        def wrapped(values: List[Value]) -> bool:
            out = []
            for v in values:
                nv = self.check_value(v)
                if nv is not None and (not f or f(nv)):
                    out.append(nv)
            if cb and out:
                return cb(out)
            return True
        return wrapped

    def _filtered_value_cb(self, cb, f=None):
        """(securedht.cpp:266-283): listen callbacks take (values, expired)."""
        def wrapped(values: List[Value], expired: bool) -> bool:
            out = []
            for v in values:
                nv = self.check_value(v)
                if nv is not None and (not f or f(nv)):
                    out.append(nv)
            if cb and out:
                return cb(out, expired)
            return True
        return wrapped

    # ------------------------------------------------------------- ops
    def get(self, key: InfoHash, get_cb=None, done_cb=None, f=None,
            where=None) -> None:
        self._dht.get(key, self._filtered_get_cb(get_cb, f), done_cb,
                      None, where)

    def query(self, key: InfoHash, query_cb, done_cb=None, q=None) -> None:
        self._dht.query(key, query_cb, done_cb, q)

    def listen(self, key: InfoHash, cb, f=None, where=None) -> int:
        return self._dht.listen(key, self._filtered_value_cb(cb, f),
                                None, where)

    def put(self, key: InfoHash, value: Value, done_cb=None,
            created: Optional[float] = None, permanent: bool = False) -> None:
        self._dht.put(key, value, done_cb, created, permanent)

    def put_signed(self, key: InfoHash, value: Value, done_cb=None,
                   permanent: bool = False) -> None:
        """Bump seq beyond local + network state, sign, put
        (securedht.cpp:318-354)."""
        if self.key is None:
            if done_cb:
                done_cb(False, [])
            return
        if value.id == Value.INVALID_ID:
            value.id = random_value_id()

        prev = self._dht.get_put(key, value.id)
        if prev is not None and value.seq <= prev.seq:
            value.seq = prev.seq + 1

        def get_cb(values: List[Value]) -> bool:
            for v in values:
                if not v.is_signed():
                    log.error("existing non-signed value at this location")
                elif v.owner is None or v.owner.get_id() != self.get_id():
                    log.error("existing signed value belongs to someone else")
                elif value.seq <= v.seq:
                    value.seq = v.seq + 1
            return True

        def done(ok, nodes):
            self.sign(value)
            self._dht.put(key, value, done_cb, None, permanent)

        self.get(key, get_cb, done, Filters.id_filter(value.id))

    def put_encrypted(self, key: InfoHash, to: InfoHash, value: Value,
                      done_cb=None, permanent: bool = False) -> None:
        """Resolve recipient key, sign + encrypt, put
        (securedht.cpp:356-374)."""
        def on_pk(pk):
            if pk is None:
                if done_cb:
                    done_cb(False, [])
                return
            try:
                ev = self.encrypt(value, pk)
            except Exception as e:
                log.error("error putting encrypted data: %s", e)
                if done_cb:
                    done_cb(False, [])
                return
            self._dht.put(key, ev, done_cb, None, permanent)

        self.find_public_key(to, on_pk)

    # ------------------------------------------------------ crypto helpers
    def sign(self, v: Value) -> None:
        if self.key is None:
            raise crypto.CryptoException("no private key")
        v.sign(self.key)

    def encrypt(self, v: Value, to) -> Value:
        if self.key is None:
            raise crypto.CryptoException("no private key")
        return v.encrypt(self.key, to)

    def decrypt(self, v: Value) -> Value:
        """(securedht.cpp:390-408)"""
        if not v.is_encrypted():
            raise crypto.CryptoException("data is not encrypted")
        plain = self.key.decrypt(v.cypher)
        ret = Value(value_id=v.id)
        ret._unpack_body(unpack_msg(plain))
        if ret.recipient != self.get_id():
            raise crypto.DecryptError("recipient mismatch")
        ret = self._parse_owner(ret)
        if ret.owner is None or not ret.check_signature():
            raise crypto.DecryptError("signature mismatch")
        return ret

    # ------------------------------------------------------ forwarding
    def __getattr__(self, name):
        # everything else (periodic, insert_node, stats, export/import,
        # cancel_*, shutdown, ...) passes straight to the wrapped Dht
        return getattr(self._dht, name)
