"""DhtRunner: the thread-safe async process runtime over real UDP sockets.

Behavioral port of the reference runtime (reference:
include/opendht/dhtrunner.h:51-497, src/dhtrunner.cpp):

- **3 threads** (dhtrunner.cpp:115-148,511-608,819-875):
  (1) receive thread — ``selectors`` on the UDP socket(s) plus a stop
  pipe, pushing raw packets into a bounded queue (RX_QUEUE_MAX_SIZE,
  packets older than 500 ms dropped under backlog, :45,414-418);
  (2) DHT thread — drain the pending-op queues (prio ops always; normal
  ops only when connected or idle-disconnected, :393-398), feed packets to
  ``Dht.periodic``, publish status changes, sleep on a condition variable
  until the scheduler's next wakeup; (3) bootstrap thread — while
  disconnected, re-resolve and ping the bootstrap nodes every
  BOOTSTRAP_PERIOD (:819-875).
- Every public API call enqueues a closure and notifies the DHT thread
  (e.g. get :610-620, put :727-750); blocking variants wrap the callback
  pair in a ``concurrent.futures.Future``.
- Non-threaded mode: construct with ``threaded=False`` and pump
  ``loop()`` manually (dhtrunner.h:361-370).
"""

from __future__ import annotations

import collections
import concurrent.futures
import logging
import os
import selectors
import socket as _socket
import threading
import time as _time
from typing import Callable, List, Optional, Tuple

from .. import (health as _health, history as _history, telemetry, tracing,
                waterfall as _waterfall)
from ..infohash import InfoHash
from ..sockaddr import SockAddr
from ..utils import TIME_MAX, lazy_module

# call-time dependency only (identity handling): lazy so the runner
# imports and runs identity-less without the `cryptography` wheel
crypto = lazy_module("opendht_tpu.crypto")
from ..core.value import Value
from ..scheduler import Scheduler
from .config import Config, NodeStatus
from .dht import Dht
from .secure_dht import SecureDht, secure_node_id

log = logging.getLogger("opendht_tpu.runner")

RX_QUEUE_MAX_SIZE = 1024 * 16          # dhtrunner.cpp:45
RX_QUEUE_MAX_DELAY = 0.5               # dhtrunner.cpp:414-418
BOOTSTRAP_PERIOD = 10.0                # dhtrunner.h:409
MAX_PACKET = 1500


def _op_trace(op: str, key, done_cb, node_id=""):
    """Mint the root client span for a public op (ISSUE-4): the span
    covers enqueue → done callback — the per-request causality anchor
    the whole wire-propagated trace hangs from.  Parentless ops consult
    the head sampler (always-on by default, rate-limited via
    ``Tracer.set_sample_rate`` / ``OPENDHT_TPU_TRACE_RATE`` in
    production); an op called under an already-active ambient context
    (e.g. a test or embedder grouping several ops into one trace)
    becomes that trace's child instead of a new root.

    Returns ``(trace_ctx_or_None, wrapped_done_cb)`` — the context is
    activated around the posted closure so ``Dht._search`` adopts it."""
    tr = tracing.get_tracer()
    if not tr.enabled:
        return None, done_cb
    sp = tr.span("dht.op." + op, parent=tracing.current(), kind="client",
                 node=node_id, op=op, key=str(key))
    if not sp:
        return None, done_cb
    fired = []

    def wrapped(ok, *args, **kw):
        if not fired:
            fired.append(True)
            sp.set(ok=bool(ok))
            sp.end()
        if done_cb:
            return done_cb(ok, *args, **kw)

    return sp.ctx, wrapped


def _op_metrics_cb(op: str, done_cb):
    """Wrap a public-API done callback with the per-op telemetry
    (ISSUE-3 request lifecycle, user view): latency from enqueue to the
    done callback — queue wait included, that IS the latency an embedder
    observes — into ``dht_op_seconds{op=}`` and the outcome into
    ``dht_ops_total{op=,ok=}``.  Multi-callback ops (a get retrying on
    both families) only time the first completion."""
    reg = telemetry.get_registry()
    if not reg.enabled:
        return done_cb
    t0 = _time.perf_counter()
    fired = []

    def wrapped(ok, *args, **kw):
        if not fired:
            fired.append(True)
            reg.histogram("dht_op_seconds", op=op).observe(
                _time.perf_counter() - t0)
            reg.counter("dht_ops_total", op=op,
                        ok="true" if ok else "false").inc()
        if done_cb:
            return done_cb(ok, *args, **kw)

    return wrapped


class RunnerConfig:
    """DhtRunner::Config (dhtrunner.h:56-61)."""

    def __init__(self, dht_config: Optional[Config] = None,
                 identity: "crypto.Identity | None" = None,
                 threaded: bool = True, proxy_server: str = "",
                 push_node_id: str = "", native_engine: bool = True,
                 native_exempt_loopback: bool = True):
        self.dht_config = dht_config or Config()
        self.identity = identity
        self.threaded = threaded
        self.proxy_server = proxy_server
        self.push_node_id = push_node_id
        #: use the C++ datagram engine (ring buffer + native ingress
        #: guards, opendht_tpu/native) for IPv4 when it is available
        self.native_engine = native_engine
        #: skip native rate limits for 127/8 sources (local clusters);
        #: disable on hosts where loopback spoofing is a concern
        self.native_exempt_loopback = native_exempt_loopback


class DhtRunner:
    """Thread-safe async façade around a SecureDht node."""

    def __init__(self):
        self._dht: Optional[SecureDht] = None
        self._health: "_health.NodeHealth | None" = None
        self._history: "_history.MetricsHistory | None" = None
        self._sock4: Optional[_socket.socket] = None
        self._sock6: Optional[_socket.socket] = None
        self._udp = None                       # native UdpEngine (IPv4)
        self._native_thread: Optional[threading.Thread] = None
        self._net_running = False
        self._stop_rd, self._stop_wr = None, None
        self.running = False
        self.bound_port = 0

        self._rcv = collections.deque()            # (recv_time, data, from)
        self._sock_lock = threading.Lock()
        self._ops_lock = threading.Lock()
        self._pending_ops: collections.deque = collections.deque()
        self._pending_ops_prio: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self._dht_thread: Optional[threading.Thread] = None
        self._rcv_thread: Optional[threading.Thread] = None
        self._bootstrap_thread: Optional[threading.Thread] = None
        self._bootstrap_nodes: List[Tuple[str, int]] = []
        self._bootstrap_all: List[Tuple[str, int]] = []
        self._bootstraping = False
        self._bootstrap_cv = threading.Condition()

        self.status4 = NodeStatus.DISCONNECTED
        self.status6 = NodeStatus.DISCONNECTED
        self.status_cb: Optional[Callable] = None
        self.on_status_changed: Optional[Callable] = None

        # proxy hot-swap state (↔ dhtrunner.cpp:992-1041)
        self.use_proxy = False
        self._proxy_dht = None                 # SecureDht over DhtProxyClient
        self._proxy_client = None
        self._listeners_lock = threading.Lock()
        self._listener_token = 1
        #: runner token → _RunnerListener (↔ DhtRunner::Listener,
        #: dhtrunner.cpp:47-54: {tokenClassicDht, tokenProxyDht, key, cb, f})
        self._listeners: dict = {}

    # ------------------------------------------------------------- lifecycle
    def run(self, port: int = 0, config: Optional[RunnerConfig] = None,
            *, ipv6: bool = False) -> None:
        """Bind sockets, build the node, start the threads
        (↔ DhtRunner::run, dhtrunner.cpp:77-149)."""
        if self.running:
            return
        config = config or RunnerConfig()
        self._config = config
        self._start_network(port, ipv6)

        dht_config = config.dht_config
        if config.identity and dht_config.node_id is None:
            dht_config.node_id = secure_node_id(config.identity[1])
        has_v6 = ipv6 and (self._sock6 is not None
                           or (self._udp is not None and self._udp.has_v6))
        dht = Dht(self._send, dht_config, Scheduler(),
                  has_v4=True, has_v6=has_v6)
        self._dht = SecureDht(dht, config.identity)
        dht.status_cb = lambda s4, s6: None   # runner tracks status itself
        dht.warmup()     # compile hot kernels before serving any packet

        # flight data recorder (round 17): the bounded ring of
        # delta-encoded registry frames, ticking on the node scheduler
        # ahead of the health job so a health window never reads frames
        # more than one period stale (host-side subtraction only — no
        # device work, kernels untouched)
        self._history = None
        hcfg = dht_config.history
        if hcfg.period > 0 and hcfg.capacity > 0:
            # the ring is frame-count-bounded while the SLO windows the
            # health engine reads through it are TIME-bounded: at a
            # short recorder period the default capacity would silently
            # truncate the slow-burn window (the private _Window kept
            # slow_window * 1.25 by time regardless of cadence), so
            # scale the capacity up to cover it (review finding)
            if dht_config.health.period > 0:
                import dataclasses
                import math as _math
                need = int(_math.ceil(
                    dht_config.health.slow_window * 1.25 / hcfg.period))
                if hcfg.capacity < need:
                    log.info("history capacity %d < slow SLO window "
                             "coverage at period %gs; raising to %d",
                             hcfg.capacity, hcfg.period, need)
                    hcfg = dataclasses.replace(hcfg, capacity=need)
            self._history = _history.MetricsHistory(
                hcfg, clock=dht.scheduler.time,
                node=str(dht.get_node_id()))
            self._history.attach(dht.scheduler)
            # the reshard tick's sustain check corroborates its latch
            # against windowed frame evidence (reshard.py) — the ring
            # is built here, after the Dht, so late-bind it
            try:
                dht.reshard.set_history(self._history)
            except AttributeError:
                pass
            # pipeline observatory (round 22): the recorder's frame
            # cadence IS the windowed-reset cadence — each committed
            # frame rolls the wave builder's windowed in-flight peak
            # and pushes an occupancy window checkpoint
            try:
                self._history.add_frame_hook(
                    lambda _frame, _wb=dht.wave_builder: _wb.frame_tick())
                # listener table (round 24): the same frame cadence
                # rolls the windowed delivery-lag p95 into the
                # dht_listener_lag_p95 gauge dhtmon gates on
                self._history.add_frame_hook(
                    lambda _frame, _lt=dht.listener_table:
                        _lt.frame_tick())
            except AttributeError:
                pass

        # health observatory (round 14): the declarative SLO engine +
        # node verdict, evaluated on a periodic scheduler tick riding
        # the same DHT thread as every other job (host-side snapshot
        # subtraction only — no device work, kernels untouched).  With
        # the recorder live, every windowed delta reads through its
        # frames (round 17 — one delta codepath) and an unhealthy
        # transition captures a black-box bundle.
        self._health = None
        if dht_config.health.period > 0:
            self._health = _health.NodeHealth(
                dht, dht_config.health, node=str(dht.get_node_id()),
                history=self._history)
            if self._history is not None:
                self._health.evaluator.on_transition = \
                    self._on_health_transition
            self._health.attach(dht.scheduler)

        # OPEN-bound tracker (round 19): periodic live comparison of
        # achieved wave p50 / occupancy / churny-static ratio against
        # the six open perf_budgets.json bounds, on the same scheduler
        # (registry reads only — no device work); re-drops the settling
        # record each tick so a smoke harvest collects fresh evidence
        self._open_bounds = None
        wcfg = getattr(dht_config, "waterfall", None)
        period = getattr(wcfg, "open_bound_period", 0.0) if wcfg else 0.0
        if period > 0:
            self._open_bounds = _waterfall.OpenBoundTracker()
            self._open_bounds.attach(dht.scheduler, period=period)

        self.running = True
        if config.threaded:
            self._dht_thread = threading.Thread(
                target=self._dht_loop, name="dht", daemon=True)
            self._dht_thread.start()
        if config.proxy_server:
            # start proxied (↔ DhtRunner::Config::proxy_server,
            # dhtrunner.cpp:98-149 → enableProxy at startup)
            self.enable_proxy(config.proxy_server)

    def _start_network(self, port: int, ipv6: bool) -> None:
        """(↔ DhtRunner::startNetwork, dhtrunner.cpp:511-608).  Both
        families go through the native C++ datagram engine when
        available (recv thread polling the v4 + v6-only sockets, ring
        buffer, martian filter and rate limits in C++; Python drains
        packet batches) and fall back to Python sockets otherwise."""
        self._net_running = True
        if self._config.native_engine:
            try:
                from ..native import UdpEngine, available
                if available():
                    # The native limits are a datagram-level flood
                    # backstop only: the protocol-level request limiting
                    # (requests-only, configurable) stays in the Python
                    # engine (net/engine.py:335).  Per-IP gets 8×
                    # headroom over the request budget (responses, NATed
                    # clusters) while global sits another 2× above it so
                    # one flooding source can never consume the whole
                    # global window; loopback exemption is a config knob
                    # (default on for local clusters).
                    budget = max(self._config.dht_config.max_req_per_sec, 8)
                    self._udp = UdpEngine(
                        port, global_rps=budget * 16,
                        per_ip_rps=budget * 8,
                        exempt_loopback=self._config.native_exempt_loopback,
                        ipv6=ipv6)
                    self.bound_port = self._udp.port
                    self._native_thread = threading.Thread(
                        target=self._native_rcv_loop, name="dht-rcv-native",
                        daemon=True)
            except (OSError, RuntimeError, ImportError):
                self._udp = None
        if self._udp is None:
            self._sock4 = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
            self._sock4.setsockopt(_socket.SOL_SOCKET,
                                   _socket.SO_REUSEADDR, 1)
            self._sock4.bind(("0.0.0.0", port))
            self.bound_port = self._sock4.getsockname()[1]
        if ipv6 and not (self._udp is not None and self._udp.has_v6):
            # v6 rides the native engine's second socket when available;
            # this Python socket is the fallback path only
            try:
                self._sock6 = _socket.socket(_socket.AF_INET6,
                                             _socket.SOCK_DGRAM)
                self._sock6.setsockopt(_socket.IPPROTO_IPV6,
                                       _socket.IPV6_V6ONLY, 1)
                self._sock6.bind(("::", self.bound_port))
            except OSError:
                self._sock6 = None
        self._stop_rd, self._stop_wr = os.pipe()
        if self._sock4 is not None or self._sock6 is not None:
            self._rcv_thread = threading.Thread(
                target=self._rcv_loop, name="dht-rcv", daemon=True)
            self._rcv_thread.start()
        if self._native_thread is not None:
            self._native_thread.start()

    def _send(self, data: bytes, dest: SockAddr) -> int:
        if self._udp is not None and (dest.family != _socket.AF_INET6
                                      or self._udp.has_v6):
            try:
                return self._udp.send(data, dest.to_tuple())
            except OSError as e:
                return e.errno or 1
        sock = self._sock6 if dest.family == _socket.AF_INET6 else self._sock4
        if sock is None:
            return 1
        try:
            sock.sendto(data, dest.to_tuple())
            return 0
        except OSError as e:
            return e.errno or 1

    # --------------------------------------------------- native rcv thread
    def _native_rcv_loop(self) -> None:
        """Drain the C++ engine's ring into the runner queue; the wait
        blocks in C++ (GIL released) until packets arrive."""
        udp = self._udp
        while self._net_running:
            try:
                if not udp.wait(0.1):
                    continue
                pkts = udp.poll(256)
            except Exception:
                if not self._net_running:
                    break
                log.exception("native rcv error; retrying")
                _time.sleep(0.1)
                continue
            if not pkts:
                continue
            # timestamp with the Python clock: the staleness check in
            # _loop compares against time.monotonic(), and the C++
            # steady_clock epoch is not guaranteed to match it
            now = _time.monotonic()
            with self._sock_lock:
                for _rx_time, data, (host, port) in pkts:
                    if len(self._rcv) < RX_QUEUE_MAX_SIZE:
                        self._rcv.append((now, data, SockAddr(host, port)))
            with self._cv:
                self._cv.notify()

    # ------------------------------------------------------------ rcv thread
    def _rcv_loop(self) -> None:
        """(↔ rcv_thread select loop, dhtrunner.cpp:544-607)"""
        sel = selectors.DefaultSelector()
        for sock in (self._sock4, self._sock6):
            if sock is not None:
                sock.setblocking(False)
                sel.register(sock, selectors.EVENT_READ)
        sel.register(self._stop_rd, selectors.EVENT_READ)
        try:
            while True:
                for key, _ in sel.select():
                    if key.fd == self._stop_rd:
                        os.read(self._stop_rd, 64)
                        return
                    try:
                        data, addr = key.fileobj.recvfrom(MAX_PACKET)
                    except OSError:
                        continue
                    if not data:
                        continue
                    with self._sock_lock:
                        if len(self._rcv) < RX_QUEUE_MAX_SIZE:
                            self._rcv.append(
                                (_time.monotonic(), data,
                                 SockAddr(addr[0], addr[1])))
                    with self._cv:
                        self._cv.notify()
        finally:
            sel.close()

    # ------------------------------------------------------------ dht thread
    def _loop(self) -> float:
        """One pump of the DHT: ops, packets, status
        (↔ DhtRunner::loop_, dhtrunner.cpp:387-445).  Returns next wakeup
        (monotonic time) or TIME_MAX."""
        dht = self._dht
        if dht is None:
            return TIME_MAX
        with self._ops_lock:
            status = self.get_status()
            ops = []
            # drain BOTH queues each pump, prio first.  The reference
            # (and this runner until round 12) skipped the normal queue
            # whenever prio ops were pending — under sustained prio
            # traffic (bootstrap ping storms, stats polls) normal ops
            # could be deferred indefinitely (starvation regression
            # test in tests/test_runner.py).  Draining prio-then-normal
            # in one pump is the fairness bound: prio keeps strict
            # precedence within the pump, and every pump makes progress
            # on eligible normal ops.
            if self._pending_ops_prio:
                ops.extend(self._pending_ops_prio)
                self._pending_ops_prio.clear()
            if self._pending_ops and (
                    self.use_proxy
                    or status is NodeStatus.CONNECTED
                    or (status is NodeStatus.DISCONNECTED
                        and not self._bootstraping)):
                ops.extend(self._pending_ops)
                self._pending_ops.clear()
        active = self._proxy_dht if self.use_proxy else dht
        for op in ops:
            try:
                op(active)
            except Exception:
                log.exception("pending op failed")

        with self._sock_lock:
            received = list(self._rcv)
            self._rcv.clear()
        wakeup = TIME_MAX
        if received:
            now = _time.monotonic()
            for rx_time, data, from_addr in received:
                if now - rx_time > RX_QUEUE_MAX_DELAY:
                    log.warning("dropping packet with high delay %.3fs",
                                now - rx_time)
                    continue
                wakeup = dht.periodic(data, from_addr)
        else:
            wakeup = dht.periodic(None, None)

        s4 = dht.get_status(_socket.AF_INET)
        s6 = dht.get_status(_socket.AF_INET6)
        if s4 is not self.status4 or s6 is not self.status6:
            self.status4, self.status6 = s4, s6
            if s4 is NodeStatus.DISCONNECTED and s6 is NodeStatus.DISCONNECTED:
                with self._bootstrap_cv:
                    self._bootstrap_nodes = list(self._bootstrap_all)
                self._try_bootstrap_continuously()
            else:
                with self._bootstrap_cv:
                    self._bootstrap_nodes = []
            cb = self.status_cb or self.on_status_changed
            if cb:
                try:
                    cb(s4, s6)
                except Exception:
                    log.exception("status callback failed")
        return wakeup

    def _dht_loop(self) -> None:
        """(↔ dht_thread body, dhtrunner.cpp:115-148)"""
        while self.running:
            try:
                wakeup = self._loop()
            except Exception:
                log.exception("dht loop error")
                wakeup = _time.monotonic() + 0.1

            def has_job():
                if not self.running:
                    return True
                with self._sock_lock:
                    if self._rcv:
                        return True
                with self._ops_lock:
                    if self._pending_ops_prio:
                        return True
                    if self._pending_ops:
                        if self.use_proxy:
                            return True
                        s = self.get_status()
                        if s is NodeStatus.CONNECTED or (
                                s is NodeStatus.DISCONNECTED
                                and not self._bootstraping):
                            return True
                return False

            with self._cv:
                if wakeup == TIME_MAX:
                    self._cv.wait_for(has_job)
                else:
                    delay = max(0.0, wakeup - _time.monotonic())
                    self._cv.wait_for(has_job, timeout=delay)

    def loop(self) -> float:
        """Non-threaded mode: pump once, return next wakeup
        (dhtrunner.h:361-370)."""
        return self._loop()

    # ------------------------------------------------------------- op queues
    def _post_node(self, op, prio: bool = False) -> None:
        """Post an op that must run on the UDP node even while the proxy
        backend is active (node-level ops: ping/insert/export — the REST
        backend has no node table)."""
        self._post(lambda _active: op(self._dht), prio)

    def _post(self, op, prio: bool = False) -> None:
        with self._ops_lock:
            (self._pending_ops_prio if prio else self._pending_ops).append(op)
        with self._cv:
            self._cv.notify()

    # ------------------------------------------------------------- bootstrap
    def bootstrap(self, host: str, port: "int | str" = 4222,
                  done_cb=None) -> None:
        """Add a bootstrap node and ping it continuously until connected
        (↔ DhtRunner::bootstrap, dhtrunner.cpp:877-931)."""
        port = int(port)
        with self._bootstrap_cv:
            self._bootstrap_all.append((host, port))
            self._bootstrap_nodes.append((host, port))
        self._ping((host, port), done_cb)
        self._try_bootstrap_continuously()

    def bootstrap_node(self, node_id: InfoHash, addr: SockAddr) -> None:
        """Insert a known node directly (no ping) — import path
        (dhtrunner.cpp:933-947)."""
        self._post_node(lambda dht: dht.insert_node(node_id, addr),
                        prio=True)

    def _ping(self, hostport: Tuple[str, int], done_cb=None) -> None:
        host, port = hostport
        try:
            addrs = SockAddr.resolve(host, port)
        except OSError:
            addrs = []
        for a in addrs:
            self._post_node(lambda dht, a=a: dht.ping_node(a, done_cb),
                            prio=True)

    def _try_bootstrap_continuously(self) -> None:
        """(↔ tryBootstrapContinuously, dhtrunner.cpp:819-875)"""
        with self._bootstrap_cv:
            if self._bootstraping or not self._bootstrap_nodes:
                return
            self._bootstraping = True

        def loop():
            while self.running:
                with self._bootstrap_cv:
                    nodes = list(self._bootstrap_nodes)
                    if not nodes:
                        break
                if self.get_status() is NodeStatus.CONNECTED:
                    break
                for hp in nodes:
                    self._ping(hp)
                with self._bootstrap_cv:
                    self._bootstrap_cv.wait(BOOTSTRAP_PERIOD)
            with self._bootstrap_cv:
                self._bootstraping = False

        self._bootstrap_thread = threading.Thread(
            target=loop, name="dht-bootstrap", daemon=True)
        self._bootstrap_thread.start()

    # ------------------------------------------------------------------ API
    def get(self, key: InfoHash, get_cb=None, done_cb=None, f=None,
            where=None) -> None:
        """(dhtrunner.cpp:610-620)"""
        done_cb = _op_metrics_cb("get", done_cb)
        tctx, done_cb = _op_trace("get", key, done_cb,
                                  str(self.get_node_id()))
        self._post(lambda dht: tracing.run_with(
            tctx, lambda: dht.get(key, get_cb, done_cb, f, where)))

    def get_sync(self, key: InfoHash, timeout: Optional[float] = 30.0,
                 f=None, where=None) -> List[Value]:
        """Blocking get: returns all values found (python binding style)."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        out: List[Value] = []
        self.get(key, lambda vals: out.extend(vals) or True,
                 lambda ok, ns: fut.done() or fut.set_result(ok), f, where)
        fut.result(timeout)
        return out

    def query(self, key: InfoHash, query_cb, done_cb=None, q=None) -> None:
        self._post(lambda dht: dht.query(key, query_cb, done_cb, q))

    def put(self, key: InfoHash, value: Value, done_cb=None,
            created: Optional[float] = None, permanent: bool = False) -> None:
        """(dhtrunner.cpp:727-750)"""
        done_cb = _op_metrics_cb("put", done_cb)
        tctx, done_cb = _op_trace("put", key, done_cb,
                                  str(self.get_node_id()))
        self._post(lambda dht: tracing.run_with(
            tctx, lambda: dht.put(key, value, done_cb, created,
                                  permanent)))

    def put_sync(self, key: InfoHash, value: Value,
                 timeout: Optional[float] = 30.0,
                 permanent: bool = False) -> bool:
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self.put(key, value,
                 lambda ok, ns: fut.done() or fut.set_result(ok),
                 permanent=permanent)
        return bool(fut.result(timeout))

    def put_signed(self, key: InfoHash, value: Value, done_cb=None,
                   permanent: bool = False) -> None:
        done_cb = _op_metrics_cb("put_signed", done_cb)
        tctx, done_cb = _op_trace("put_signed", key, done_cb,
                                  str(self.get_node_id()))
        self._post(lambda dht: tracing.run_with(
            tctx, lambda: dht.put_signed(key, value, done_cb, permanent)))

    def put_encrypted(self, key: InfoHash, to: InfoHash, value: Value,
                      done_cb=None, permanent: bool = False) -> None:
        done_cb = _op_metrics_cb("put_encrypted", done_cb)
        tctx, done_cb = _op_trace("put_encrypted", key, done_cb,
                                  str(self.get_node_id()))
        self._post(lambda dht: tracing.run_with(
            tctx, lambda: dht.put_encrypted(key, to, value, done_cb,
                                            permanent)))

    def cancel_put(self, key: InfoHash, vid: int) -> None:
        self._post(lambda dht: dht.cancel_put(key, vid))

    def listen(self, key: InfoHash, cb, f=None,
               where=None) -> concurrent.futures.Future:
        """Returns a Future resolving to the (runner-level) listen token
        (↔ DhtRunner::listen futures, dhtrunner.cpp:638-671).  The runner
        keeps the listener record so subscriptions survive a proxy
        hot-swap (↔ DhtRunner::Listener, dhtrunner.cpp:47-54)."""
        fut: concurrent.futures.Future = concurrent.futures.Future()

        # Dedup wrapper: a backend swap replays current values on the new
        # subscription; remember what this runner-level listener already
        # delivered so user callbacks fire once per value (the role the
        # reference's per-listener OpValueCache plays).
        seen: dict = {}

        def wrapped_cb(values, expired):
            out = []
            for v in values:
                if expired:
                    seen.pop(v.id, None)
                    out.append(v)
                else:
                    prev = seen.get(v.id)
                    if prev is not None and prev == v:
                        continue
                    seen[v.id] = v
                    out.append(v)
            if not out:
                return True
            return cb(out, expired)

        # base callback is a no-op so listen_done stays callable even
        # when the registry is disabled (_op_metrics_cb passes the base
        # through untouched in that case)
        listen_done = _op_metrics_cb("listen", lambda ok, *a, **kw: None)
        tctx, listen_done = _op_trace("listen", key, listen_done,
                                      str(self.get_node_id()))

        def op(dht):
            backend_token = tracing.run_with(
                tctx, lambda: dht.listen(key, wrapped_cb, f, where))
            if backend_token is None:
                # shed at ingest admission (Dht.listen's None sentinel,
                # round 12): no subscription exists — do not register a
                # runner record that a proxy hot-swap would faithfully
                # re-subscribe; surface the shed as a 0 future result.
                # (A backend return of 0 is DIFFERENT: the listener
                # consumed local values and stopped — a satisfied op,
                # which keeps the pre-existing success path below.)
                listen_done(False)
                fut.set_result(0)
                return
            with self._listeners_lock:
                token = self._listener_token
                self._listener_token += 1
                self._listeners[token] = {
                    "key": key, "cb": wrapped_cb, "f": f, "where": where,
                    "backend_token": backend_token,
                    "on_proxy": self.use_proxy,
                }
            # registration latency (enqueue → backend token issued)
            listen_done(backend_token is not None)
            fut.set_result(token)

        self._post(op)
        return fut

    def cancel_listen(self, key: InfoHash, token) -> None:
        def op(dht):
            t = (token.result(0)
                 if isinstance(token, concurrent.futures.Future) else token)
            with self._listeners_lock:
                rec = self._listeners.pop(t, None)
            if rec is not None:
                dht.cancel_listen(rec["key"], rec["backend_token"])
            # unknown runner tokens are dropped: forwarding them into the
            # backend token namespace could cancel someone else's listener

        self._post(op)

    # ----------------------------------------------------------- proxy swap
    def enable_proxy(self, proxy: "str | None") -> None:
        """Hot-swap the backend between the UDP node and a REST proxy
        client, re-registering every live listener on the new backend
        (↔ DhtRunner::enableProxy, dhtrunner.cpp:992-1041).

        ``proxy`` is "host:port" (or "http://host:port") to enable,
        None/"" to fall back to the UDP node.
        """
        def op(_dht):
            from ..proxy.client import DhtProxyClient

            old = self._proxy_dht if self.use_proxy else self._dht
            old_client = self._proxy_client
            if proxy:
                spec = proxy
                for prefix in ("http://", "https://"):
                    if spec.startswith(prefix):
                        spec = spec[len(prefix):]
                spec = spec.rstrip("/")
                # host[:port], [v6]:port, bare v6 literal, bare host
                if spec.startswith("["):                   # [::1]:8080
                    host, _, rest = spec[1:].partition("]")
                    port_s = rest.lstrip(":")
                elif spec.count(":") == 1:                 # host:port
                    host, _, port_s = spec.partition(":")
                else:                                      # bare host / v6
                    host, port_s = spec, ""
                try:
                    port_n = int(port_s) if port_s else 8080
                except ValueError:
                    log.error("enable_proxy: invalid proxy spec %r", proxy)
                    return
                client = DhtProxyClient(host or "127.0.0.1", port_n,
                                        client_id=self._config.push_node_id)
                ident = self._config.identity
                new = SecureDht(client,
                                (ident.first, ident.second) if ident else None)
                self._proxy_client = client
                self._proxy_dht = new
                self.use_proxy = True
            else:
                if not self.use_proxy:
                    return
                new = self._dht
                self.use_proxy = False
            # re-register listeners on the new backend (:1005-1032).
            # Established subscriptions were admitted when created:
            # exempt the re-subscribes from ingest admission so a full
            # queue at swap time cannot shed them (round 12 — shed at
            # admission only, never an existing listener)
            import contextlib
            wb = getattr(new, "wave_builder", None)
            exempt = wb.exempt() if wb is not None else \
                contextlib.nullcontext()
            with self._listeners_lock:
                recs = list(self._listeners.values())
            with exempt:
                for rec in recs:
                    try:
                        old.cancel_listen(rec["key"], rec["backend_token"])
                    except Exception:
                        pass
                    rec["backend_token"] = new.listen(
                        rec["key"], rec["cb"], rec["f"], rec["where"])
                    rec["on_proxy"] = self.use_proxy
            # retire the previous proxy client (proxy→proxy swap or
            # fall-back to UDP): stop its maintenance/long-poll threads
            if old_client is not None and old_client is not self._proxy_client:
                old_client.join()
            if not proxy and self._proxy_client is not None:
                self._proxy_client.join()
                self._proxy_client = None
                self._proxy_dht = None

        self._post(op, prio=True)

    def find_certificate(self, node: InfoHash, cb) -> None:
        self._post(lambda dht: dht.find_certificate(node, cb))

    def find_public_key(self, node: InfoHash, cb) -> None:
        self._post(lambda dht: dht.find_public_key(node, cb))

    # ----------------------------------------------------------- inspection
    def get_status(self, af: int = 0) -> NodeStatus:
        """Best status across families (dhtrunner.h:165-172); when the
        proxy backend is active, its connectivity is the node's status."""
        if self.use_proxy and self._proxy_dht is not None:
            return self._proxy_dht.get_status(af)
        if af == _socket.AF_INET:
            return self.status4
        if af == _socket.AF_INET6:
            return self.status6
        return (self.status4 if self.status4.value >= self.status6.value
                else self.status6)

    def is_running(self) -> bool:
        return self.running

    def get_id(self) -> InfoHash:
        return self._dht.get_id() if self._dht else InfoHash()

    def get_node_id(self) -> InfoHash:
        return self._dht.get_node_id() if self._dht else InfoHash()

    def get_bound_port(self) -> int:
        return self.bound_port

    def get_node_stats(self, af: int = _socket.AF_INET):
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._post(lambda dht: fut.set_result(dht.get_nodes_stats(af)),
                   prio=True)
        return fut.result(10.0)

    def get_metrics(self) -> dict:
        """JSON snapshot of the unified telemetry registry (ISSUE-3) —
        the SAME registry the proxy's ``GET /stats`` route serves as
        Prometheus text.  Refreshes the routing-table health gauges
        (``dht_routing_*{family=}`` — the ``get_nodes_stats`` island
        folded into the spine, ↔ Dht::getNodesStats) before dumping, so
        a scrape always sees current table state alongside the
        cumulative counters/histograms."""
        reg = telemetry.get_registry()
        if self.running and self._dht is not None:
            for af, fam in ((_socket.AF_INET, "ipv4"),
                            (_socket.AF_INET6, "ipv6")):
                try:
                    st = self.get_node_stats(af)
                except Exception:
                    continue
                for field, v in st.to_dict().items():
                    reg.gauge("dht_routing_" + field, family=fam).set(v)
        # kernel cost ledger (ISSUE-6): publish dht_kernel_* gauges when
        # the ledger has been computed (REPL `kernels`, scanner, CI) or
        # OPENDHT_TPU_LEDGER=1 arms eager compute — a no-op dict check
        # otherwise, so a bare scrape stays cheap
        try:
            from .. import profiling
            profiling.maybe_export(reg)
        except Exception:
            pass
        return reg.snapshot()

    def get_health(self) -> dict:
        """The node's current health report (ISSUE-9): the verdict
        (``healthy | degraded | unhealthy``; ``unknown`` before the
        first tick or with ``health.period = 0``) plus per-signal and
        per-SLO attribution — the JSON the proxy's ``GET /healthz``
        route serves and the ``health`` REPL command prints."""
        h = self._health
        if h is None:
            return {"verdict": "unknown", "enabled": False,
                    "signals": {}, "slo": {}, "unknown": []}
        rep = dict(h.report())
        rep["enabled"] = True
        return rep

    def get_history(self, since: Optional[float] = None,
                    limit: Optional[int] = None) -> dict:
        """The flight data recorder's retained frames (round 17): the
        JSON the proxy's ``GET /history`` route serves and ``dhtmon
        --window/--since`` evaluate windowed invariants over.
        ``since`` keeps frames from the last SEC seconds (recorder
        clock), ``limit`` the newest N.  The envelope carries the
        server's wall/mono clocks so the cluster timeline assembler
        can estimate scrape skew."""
        h = self._history
        if h is None:
            return {"enabled": False, "frames": []}
        t0 = (h.clock() - since) if since is not None else None
        doc = h.meta()
        doc["node_id"] = self.get_node_id().hex()
        doc["time"] = _time.time()
        doc["mono"] = h.clock()
        doc["frames"] = h.frames(t0=t0, limit=limit)
        return doc

    def dump_bundle(self, reason: str = "on_demand", *,
                    refresh: bool = True) -> dict:
        """Assemble one post-mortem black-box bundle (round 17): the
        last N history frames + the flight-recorder ring (spans AND
        events) + kernel ledger + keyspace/cache/ingest snapshots +
        the health report in ONE JSON artifact — the reference's
        ``dumpTables`` instant, retained and machine-readable.  Served
        by proxy ``GET /debug/bundle``, the ``bundle`` REPL cmd and
        ``dhtscanner --bundle DIR``; captured automatically (with
        ``refresh=False``) on every health transition to unhealthy.

        ``refresh=False`` skips the routing-gauge refresh, which posts
        to the DHT thread and waits — REQUIRED when called FROM that
        thread (the health tick's transition hook), where the wait
        would deadlock."""
        metrics: dict = {}
        try:
            metrics = (self.get_metrics() if refresh
                       else telemetry.get_registry().snapshot())
        except Exception:
            pass
        ingest: dict = {}
        try:
            ingest = self._dht.wave_builder.snapshot()
        except Exception:
            pass
        return _history.build_bundle(
            reason=reason,
            node_id=self.get_node_id().hex(),
            status=self.get_status().name,
            history=self._history,
            health=self.get_health(),
            metrics=metrics,
            keyspace=self.get_keyspace(),
            cache=self.get_cache(),
            ingest=ingest,
            waterfall=self.get_profile(),
            pipeline=self.get_pipeline(),
            peers=self.get_peers(),
            listeners=self.get_listeners(),
        )

    def get_bundles(self) -> list:
        """Auto-captured black-box bundles (newest last; bounded by
        ``history.retain_bundles``) — the evidence retained from past
        unhealthy transitions."""
        return self._history.bundles() if self._history is not None else []

    def _on_health_transition(self, prev: str, new: str,
                              report: dict) -> None:
        """Evaluator transition hook (runs ON the DHT thread inside
        the health tick): capture the black-box bundle the moment the
        verdict goes unhealthy — by the time a human looks, the
        counters have moved on but the bundle has the frames."""
        if new != _health.UNHEALTHY or self._history is None:
            return
        try:
            b = self.dump_bundle(reason="health_transition",
                                 refresh=False)
            b["transition"] = {"from": prev, "to": new,
                               "causes": report.get("causes", [])}
            self._history.store_bundle(b)
        except Exception:
            log.exception("black-box bundle capture failed")

    def get_keyspace(self) -> dict:
        """The keyspace traffic observatory snapshot (ISSUE-10): the
        256-bin keyspace histogram, the heavy-hitter top-K with
        windowed estimates/shares, and the per-shard load attribution
        + imbalance ratio — the JSON the proxy's ``GET /keyspace``
        route serves, the ``keyspace`` REPL command prints, and the
        scanner's ``keyspace`` section embeds."""
        try:
            ks = getattr(self._dht, "keyspace", None)
            if ks is None:
                return {"enabled": False}
            return ks.snapshot()
        except Exception:
            return {"enabled": False}

    def get_reshard(self) -> dict:
        """The load-aware resharding snapshot (ISSUE-17): installed
        layout generation + edges, tick/swap/skip counters (skips
        reason-labeled), the sustain latch age and the post-swap
        refolded imbalance — the JSON the proxy's ``GET /reshard``
        route serves, the ``reshard`` REPL command prints, and the
        scanner's ``reshard`` section embeds."""
        try:
            rs = getattr(self._dht, "reshard", None)
            if rs is None:
                return {"enabled": False}
            return rs.snapshot()
        except Exception:
            return {"enabled": False}

    def get_cache(self) -> dict:
        """The hot-key serving cache snapshot (ISSUE-11): occupancy,
        per-entry hit counts, windowed hit ratio, invalidation/eviction
        totals and the current widened hot set — the JSON the proxy's
        ``GET /cache`` route serves, the ``cache`` REPL command prints,
        and the scanner's ``cache`` section embeds."""
        try:
            hc = getattr(self._dht, "hotcache", None)
            if hc is None:
                return {"enabled": False}
            return hc.snapshot()
        except Exception:
            return {"enabled": False}

    def get_profile(self) -> dict:
        """The per-op latency waterfall snapshot (ISSUE-15): per-stage
        ``dht_stage_seconds`` histograms with p50/p95/p99 and bucket
        exemplars, the stage budgets, the recent per-op decomposition
        records and the live OPEN-bound comparison — the JSON the
        proxy's ``GET /profile`` route serves, the ``profile`` REPL
        command prints, and the scanner's ``waterfall`` section
        embeds."""
        try:
            doc = _waterfall.get_profiler().snapshot()
            if self._open_bounds is not None:
                doc["open_bounds"] = self._open_bounds.snapshot()
            return doc
        except Exception:
            return {"enabled": False}

    def get_pipeline(self) -> dict:
        """The pipeline utilization snapshot (ISSUE-18): the windowed
        device-occupancy gauge, per-cause bubble attribution, measured
        fill∥device overlap ratio and the pipeline shape (depth /
        in-flight / windowed peak) — the JSON the proxy's ``GET
        /pipeline`` route serves, the ``pipeline`` REPL command
        prints, and the scanner's ``pipeline`` section embeds."""
        try:
            wb = getattr(self._dht, "wave_builder", None)
            if wb is None:
                return {"enabled": False}
            return wb.pipeline_snapshot()
        except Exception:
            return {"enabled": False}

    def get_peers(self) -> dict:
        """The per-peer network observatory snapshot (ISSUE-19):
        per-peer srtt/rttvar/RTO, request outcome counts, attempt
        timeouts + spurious retransmits, bytes in/out by message type
        and good<->dubious<->expired flap transitions — the JSON the
        proxy's ``GET /peers`` route serves, the ``peers`` REPL
        command prints, the scanner's ``peers`` section embeds and
        ``testing/wiremap_assembler.py`` folds into the cluster wire
        map."""
        try:
            led = getattr(self._dht, "peers", None)
            if led is None:
                return {"enabled": False}
            return led.snapshot()
        except Exception:
            return {"enabled": False}

    def get_listeners(self) -> dict:
        """The wave-scale listener-table snapshot (ISSUE-20):
        occupancy/tombstones/overflow of the device key-id table,
        buffered puts, match/flush/delivery counters, the windowed
        delivery-lag p95 and the soonest-expiring entries — the JSON
        the proxy's ``GET /listeners`` route serves, the ``listeners``
        REPL command prints, and the scanner's ``listeners`` section
        embeds."""
        try:
            lt = getattr(self._dht, "listener_table", None)
            if lt is None:
                return {"enabled": False}
            return lt.snapshot()
        except Exception:
            return {"enabled": False}

    def get_pipeline_trace(self) -> dict:
        """Perfetto lane export of the retained wave timeline (``GET
        /pipeline?fmt=trace``): one pid per lane (fill / device /
        drain), waves as slices linked to their ``dht.search.wave``
        spans.  Empty trace when the observatory is off."""
        try:
            obs = getattr(self._dht.wave_builder, "observatory", None)
            if obs is None or not obs.enabled:
                return {"traceEvents": [], "displayTimeUnit": "ms"}
            return obs.chrome_trace()
        except Exception:
            return {"traceEvents": [], "displayTimeUnit": "ms"}

    def get_trace(self, trace_id) -> list:
        """JSON-able span list of one distributed trace (ISSUE-4): the
        op root span plus every per-hop client span this node sent and
        every server span it recorded for that trace.  ``trace_id``
        accepts an int, a 32-hex string, or a TraceContext.  The
        cross-node assembler (testing/trace_assembler.py) calls this on
        every cluster node and stitches the full tree."""
        return tracing.get_tracer().spans(trace_id)

    def get_flight_recorder(self, limit: "int | None" = None,
                            name: "str | None" = None) -> dict:
        """The bounded-ring flight recorder dump (↔ the reference's
        ``Dht::dumpTables`` postmortem surface, structured): last-N
        spans + events (request transitions, timeouts, rate-limit
        drops, compactions, churn swaps, health transitions).

        ``name`` filters by event/span name substring at DUMP time
        (e.g. ``"health"`` keeps ``health_transition`` events and
        nothing else) — the ring itself is untouched, so eviction
        order is identical with or without a filter (ISSUE-9
        satellite; pinned in tests/test_health.py)."""
        d = tracing.get_tracer().dump(name=name)
        if limit:
            d["spans"] = d["spans"][-limit:]
            d["events"] = d["events"][-limit:]
        return d

    def get_node_message_stats(self, incoming: bool = False) -> list:
        """[ping, find, get, listen, put] counters
        (↔ DhtRunner::getNodeMessageStats, dhtrunner.cpp:317-321)."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._post(lambda dht: fut.set_result(
            dht.engine.get_node_message_stats(incoming)
            if hasattr(dht, "engine") else []), prio=True)
        return fut.result(10.0)

    def get_searches_log(self, af: int = 0) -> str:
        """(↔ DhtRunner::getSearchesLog, dhtrunner.cpp:305-309)."""
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._post(lambda dht: fut.set_result(dht.get_searches_log(af)),
                   prio=True)
        return fut.result(10.0)

    def export_nodes(self) -> list:
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._post_node(lambda dht: fut.set_result(dht.export_nodes()),
                        prio=True)
        return fut.result(10.0)

    def export_values(self) -> list:
        fut: concurrent.futures.Future = concurrent.futures.Future()
        self._post_node(lambda dht: fut.set_result(dht.export_values()),
                        prio=True)
        return fut.result(10.0)

    def import_values(self, values: list) -> None:
        self._post_node(lambda dht: dht.import_values(values), prio=True)

    # ------------------------------------------------------------- shutdown
    def shutdown(self, cb=None) -> None:
        """Graceful stop of ongoing operations (dhtrunner.cpp:1060-1081)."""
        if not self.running:
            if cb:
                cb()
            return
        self._post(lambda dht: dht.shutdown(cb), prio=True)

    def join(self) -> None:
        """Stop threads, close sockets (↔ DhtRunner::join,
        dhtrunner.cpp:151-195)."""
        self.running = False
        self._net_running = False
        with self._cv:
            self._cv.notify_all()
        with self._bootstrap_cv:
            self._bootstrap_cv.notify_all()
        if self._stop_wr is not None:
            try:
                os.write(self._stop_wr, b"x")
            except OSError:
                pass
        for t in (self._dht_thread, self._rcv_thread,
                  self._native_thread, self._bootstrap_thread):
            if t is not None and t.is_alive():
                t.join(timeout=5.0)
        for sock in (self._sock4, self._sock6):
            if sock is not None:
                sock.close()
        self._sock4 = self._sock6 = None
        if self._udp is not None:
            if self._native_thread is not None and \
                    self._native_thread.is_alive():
                # receiver thread failed to join within timeout and may
                # still be blocked in the engine: freeing it would be a
                # use-after-free, so leak the handle instead
                log.warning("native receiver thread did not join; "
                            "leaking UDP engine handle")
                self._udp.detach()
            else:
                self._udp.close()
            self._udp = None
        self._native_thread = None
        if self._stop_rd is not None:
            os.close(self._stop_rd)
            os.close(self._stop_wr)
            self._stop_rd = self._stop_wr = None
        with self._ops_lock:
            self._pending_ops.clear()
            self._pending_ops_prio.clear()
        if self._proxy_client is not None:
            self._proxy_client.join()
            self._proxy_client = None
            self._proxy_dht = None
        self.use_proxy = False
        self._dht = None
