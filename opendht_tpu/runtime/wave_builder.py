"""Continuous-batching ingest: coalesce live lookups into shared waves.

Five rounds of kernel work made the device side of a lookup a ``[Q]``
wave (``find_closest_nodes_batched`` → one lane-padded top-k launch for
*many* targets), but live traffic never fed it one: every proxy/REST
request, UDP op and embedder ``get/put/listen`` reached the table
through a per-search refill — a Q=1 launch paying the full 128-lane
padding tax per op.  Benchmarks batched; the service did not.

This module is the ingest layer that closes that gap (ROADMAP item 2),
the same iteration-level insight that made continuous batching the
serving architecture for LLM engines (Orca-style: admit work
mid-flight, keep launches full, never barrier a wave on its slowest
member):

- :class:`WaveBuilder` owns a bounded admission queue of pending
  closest-node lookups (search refills, from ALL traffic sources — the
  runner op queue, the proxy server, the UDP reply path's search
  stepping).  A wave fires when the queue reaches the **fill target Q**
  or when the oldest entry has waited the **deadline knob** (1–5 ms,
  both ``runtime/config.py`` fields), whichever comes first — one
  ``find_closest_nodes_batched`` launch per (family, k) group, results
  scattered back to each search's callback.  An op that joins after a
  wave departed simply rides the next one at whatever round it is on:
  continuous batching, not stop-and-go batch barriers.
- **Backpressure sheds at admission, never mid-search**: NEW ops are
  refused (``admit``) when the queue exceeds ``ingest_queue_max`` or
  the optional ``ingest_admit_per_sec`` sliding-window quota (the same
  :class:`~opendht_tpu.rate_limiter.RateLimiter` the net engine's
  ingress path uses, and the same counted-drop discipline as its
  ``dht_net_ratelimit_drops_total``) — an admitted search's refills
  are always queued, so backpressure can never fail an in-flight
  search.
- ``ingest_batching="off"`` is the escape hatch: ``submit`` resolves
  synchronously through the identical per-op ``[1]`` launch the
  pre-round-12 path issued — pinned result-equivalent in
  tests/test_wave_builder.py and the burst-ingest CI smoke
  (testing/ingest_smoke.py).
- **Depth-2+ wave pipeline** (round 20, ``ingest_pipeline_depth``):
  wave N's ``[Q]`` launch is dispatched *asynchronously*
  (``Dht.find_closest_nodes_launch`` — JAX async dispatch; the
  blocking transfer is deferred into the handle's ``consume()``), so
  the builder fills wave N+1 from the admission queue while N runs on
  device and drains wave N−1's scatter fan-out from a dedicated
  drainer job — host callback loops never sit between two launches.
  A wave whose handle is already ready at launch time (the host-scan
  regime: live-protocol tables) drains inline, so small-table latency
  is exactly the depth-1 path's.  ``ingest_pipeline_depth=1`` is the
  escape hatch (launch→block→scatter inline, the exact pre-round-20
  behavior); depth 2+ is pinned bit-identical to depth 1 on results,
  listener deliveries and exported storage
  (tests/test_wave_builder.py, testing/pipeline_smoke.py).
  In-flight waves are visible as the ``dht_ingest_pipeline_inflight``
  gauge (+ ``_peak``) and the per-wave ``pipeline_slot`` attr on the
  ``dht.search.wave`` ingest span.
- Observability on the PR-3/PR-4/PR-6 spine: ``dht_ingest_queue_depth``
  gauge, ``dht_ingest_wave_occupancy`` / ``dht_ingest_queue_seconds`` /
  ``dht_ingest_wave_seconds`` histograms, shed/wave/op counters, a
  ``dht.search.wave`` (mode="ingest") trace span per launch with each
  carried op's ``dht.ingest.op`` span linked to it, and the canonical
  launch shape cost-gated from day one (profiling.py
  ``wave_builder_lookup`` ↔ perf_budgets.json).

Threading: the builder lives on the DHT thread like everything else in
``runtime/dht.py`` — submissions come from posted closures, packet
handlers and scheduler jobs, and the wave trigger is itself a scheduler
job, so there are no locks and no re-entrancy (a fill-triggered wave
fires on the *next* scheduler pump, never synchronously inside the
submit that filled it).

Reference mapping: ``DhtRunner::loop_`` (dhtrunner.cpp:387-445) drains
all pending op *closures* onto one thread per pump — coalescing in
time, op by op.  The TPU design deliberately diverges: we coalesce the
ops' *device lookups* onto one launch (coalescing in the lane
dimension), because here the padded launch — not the thread hop — is
the per-op tax.  See PARITY.md "Continuous-batching ingest".
"""

from __future__ import annotations

import logging
import time as _time
from collections import deque
from typing import Callable, List

from .. import telemetry, tracing, waterfall
from ..infohash import InfoHash
from ..pipeline_observatory import PipelineObservatory, PipelineObservatoryConfig
from ..rate_limiter import RateLimiter

log = logging.getLogger("opendht_tpu.ingest")

#: failed-launch re-queues per entry before scattering empty (a
#: transient device error retries on later waves; a persistent one
#: fails the carried ops honestly after this many attempts)
_LAUNCH_RETRIES = 2


class _Entry:
    """One queued lookup: target → per-search scatter callback.

    ``t_enq`` is scheduler time (drives the deadline trigger);
    ``t_wall`` is the wall clock at submit — the honest enqueue stamp
    for the time-in-queue histogram and the ``dht.ingest.op`` span.
    The two deliberately differ: the runner drains op closures BEFORE
    ``periodic()`` re-syncs the scheduler clock, so scheduler time at
    submit can be stale by a whole sleep — reconstructing span starts
    from it put a child span seconds before its parent (caught by the
    cross-node assembler's monotonicity check)."""

    __slots__ = ("target", "af", "k", "cb", "t_enq", "t_wall", "ctx",
                 "kind", "retries", "cache_cb")

    def __init__(self, target: InfoHash, af: int, k: int, cb: Callable,
                 t_enq: float, t_wall: float, ctx, kind: str,
                 cache_cb: "Callable | None" = None):
        self.target = target
        self.af = af
        self.k = k
        self.cb = cb
        self.t_enq = t_enq
        self.t_wall = t_wall
        self.ctx = ctx
        self.kind = kind
        self.retries = 0              # failed-launch re-queues so far
        # round 16 (ISSUE-11): non-None marks a CACHE-ELIGIBLE entry (a
        # pure-get refill) — a hot-cache probe hit calls cache_cb(values)
        # and the entry never joins the lookup launch
        self.cache_cb = cache_cb


class _InflightWave:
    """One dispatched-but-not-consumed wave (round-20 pipeline):
    everything the drain step needs to scatter exactly as the
    synchronous path would have — including the per-launch shard width
    (on the handle) and the dispatch stamp/cost, so the waterfall's
    device stage can be observed at consume."""

    __slots__ = ("af", "k", "entries", "handle", "t_dispatch",
                 "dispatch_s", "t_pick", "probe_s", "slot", "seq")

    def __init__(self, af: int, k: int, entries: List[_Entry], handle,
                 t_dispatch: float, dispatch_s: float, t_pick: float,
                 probe_s: float, slot: int, seq: int = -1):
        self.af = af
        self.k = k
        self.entries = entries
        self.handle = handle          # runtime/dht.py BatchedResolve
        self.t_dispatch = t_dispatch  # wall clock at dispatch
        self.dispatch_s = dispatch_s  # host cost of the async dispatch
        self.t_pick = t_pick          # wall clock at wave pickup
        self.probe_s = probe_s        # cache-probe share of this wave
        self.slot = slot              # waves already in flight at launch
        self.seq = seq                # pipeline-observatory wave id


class WaveBuilder:
    """Fill-or-deadline-triggered aggregator over
    ``Dht.find_closest_nodes_batched`` (see module docstring)."""

    def __init__(self, dht, config):
        self._dht = dht
        self.enabled = getattr(config, "ingest_batching", "on") != "off"
        self.fill_target = max(1, int(
            getattr(config, "ingest_fill_target", 64)))
        self.deadline = float(getattr(config, "ingest_deadline", 0.002))
        self.queue_max = int(getattr(config, "ingest_queue_max", 4096))
        admit_qps = int(getattr(config, "ingest_admit_per_sec", 0) or 0)
        self._admit_limiter = (RateLimiter(admit_qps) if admit_qps > 0
                               else None)
        # round 20: waves in flight on device at once; 1 = the exact
        # pre-pipeline launch→block→scatter path (validated ≥ 1 here —
        # a zero/negative knob silently falling back to 2 would hide a
        # config typo behind the default)
        self.pipeline_depth = max(1, int(
            getattr(config, "ingest_pipeline_depth", 2) or 1))
        self._pending: deque = deque()
        self._inflight: deque = deque()   # _InflightWave, oldest first
        self._job = None              # armed scheduler Job or None
        self._drain_job = None        # armed drainer Job or None
        self._exempt = 0              # admission suspended (see exempt())
        self.waves = 0                # launches issued (cheap introspection)
        # windowed in-flight peak (round 22): high-water since the last
        # history frame; _peak_prev retains the previous frame so the
        # gauge never blinks to 0 mid-window (frame_tick rolls both)
        self.inflight_peak = 0
        self._peak_prev = 0
        # round 22: the pipeline utilization observatory — lane
        # timelines, device occupancy, bubble attribution.  Host-side
        # edge bookkeeping only; kernels stay bit-identical.
        pcfg = getattr(config, "pipeline", None)
        self.observatory = PipelineObservatory(
            pcfg if pcfg is not None else PipelineObservatoryConfig())

        reg = telemetry.get_registry()
        self._m_depth = reg.gauge("dht_ingest_queue_depth")
        self._m_inflight = reg.gauge("dht_ingest_pipeline_inflight")
        self._m_inflight_peak = reg.gauge("dht_ingest_pipeline_inflight_peak")
        self._m_inflight.set(0)
        self._m_inflight_peak.set(0)
        self._m_wave_s = reg.histogram("dht_ingest_wave_seconds")
        self._m_occupancy = reg.histogram("dht_ingest_wave_occupancy")
        self._m_queue_s = reg.histogram("dht_ingest_queue_seconds")
        self._m_waves = reg.counter("dht_ingest_waves_total")
        # round 13: waves whose resolve ran against the t-sharded table
        # (config.resolve_mesh_t) — the occupancy/latency histograms
        # above cover both modes; this counter says which mode served
        self._m_sharded_waves = reg.counter("dht_ingest_sharded_waves_total")
        self._m_ops = {}              # kind -> counter (cached handles)
        self._m_sheds = {}            # reason -> counter

    # ------------------------------------------------------------ admission
    def exempt(self):
        """Context manager: suspend admission control for internal
        continuations of ALREADY-admitted work — the proxy hot-swap
        re-registering established listeners on the new backend must
        never be shed (the subscription was admitted when it was
        created; dropping it on swap would violate the never-mid-search
        discipline)."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            self._exempt += 1
            try:
                yield
            finally:
                self._exempt -= 1
        return _ctx()

    def admit(self, op: str) -> bool:
        """Admission check for a NEW public op (get/put/listen/query).
        False ⇒ the op must be refused *now*, with a counted drop —
        the only place backpressure acts, so a search that got in can
        always finish (its refills bypass this check via
        :meth:`submit`).  With batching off there is no queue to
        protect and every op is admitted (the per-op path's behavior,
        kept result-equivalent)."""
        if not self.enabled or self._exempt:
            return True
        if len(self._pending) >= self.queue_max:
            self._shed(op, "queue_full")
            return False
        if self._admit_limiter is not None and not self._admit_limiter.limit(
                self._dht.scheduler.time()):
            self._shed(op, "rate")
            return False
        return True

    def _shed(self, op: str, reason: str) -> None:
        c = self._m_sheds.get((op, reason))
        if c is None:
            c = self._m_sheds[(op, reason)] = telemetry.get_registry(
            ).counter("dht_ingest_sheds_total", op=op, reason=reason)
        c.inc()
        tr = tracing.get_tracer()
        if tr.enabled:
            tr.event("ingest_shed", op=op, reason=reason,
                     depth=len(self._pending))
        log.debug("ingest shed %s (%s, depth=%d)", op, reason,
                  len(self._pending))

    # ------------------------------------------------------------- ingest
    def submit(self, target: InfoHash, af: int, k: int,
               cb: Callable[[List], None], *, kind: str = "refill",
               cache_cb: "Callable | None" = None) -> None:
        """Queue one closest-``k`` lookup for ``target``; ``cb(nodes)``
        fires from the wave that carries it (immediately, with the
        identical per-op launch, when batching is off).  Never sheds —
        admission already happened at the op boundary.

        ``cache_cb`` (round 16) marks the entry cache-eligible: the
        pre-launch hot-cache probe may serve it values instead of nodes
        (``_serve_cached``), in which case it never joins the launch."""
        if not self.enabled:
            # escape hatch: the per-op [1] launch — the keyspace
            # observatory still sees the target (its surfaces must not
            # go dark when batching is off; results are untouched)
            ks = getattr(self._dht, "keyspace", None)
            if ks is not None:
                ks.observe_hashes([target])
            cb(self._dht.find_closest_nodes_batched([target], af, k)[0])
            return
        now = self._dht.scheduler.time()
        t_wall = _time.time()
        if not self._pending:
            # queue went 0 -> 1: the next wave starts batching here —
            # the fill_start edge of its lane timeline
            self.observatory.note_fill_start(t_wall)
        self._pending.append(_Entry(target, af, k, cb, now, t_wall,
                                    tracing.current(), kind, cache_cb))
        depth = len(self._pending)
        self._m_depth.set(depth)
        c = self._m_ops.get(kind)
        if c is None:
            c = self._m_ops[kind] = telemetry.get_registry().counter(
                "dht_ingest_ops_total", kind=kind)
        c.inc()
        # fill target ⇒ pull the trigger to *now* (the next scheduler
        # pump — never synchronously inside a submit, see module doc);
        # otherwise make sure a deadline trigger covers the new oldest
        self._arm(now if depth >= self.fill_target
                  else self._pending[0].t_enq + self.deadline)

    def _arm(self, t: float) -> None:
        job = self._job
        if job is not None and not job.cancelled:
            # job.time is None while the scheduler has the job in its
            # CURRENT due sweep (run() nulls the time before executing,
            # scheduler.py) — a submit() from a sibling due job lands
            # here; the wave fires later this same sweep and drains the
            # new entry, so nothing to reschedule
            if job.time is not None and t < job.time:
                self._job = self._dht.scheduler.edit(job, t)
        else:
            self._job = self._dht.scheduler.add(t, self._fire)

    def pending(self) -> int:
        return len(self._pending)

    # --------------------------------------------------------------- waves
    def _fire(self) -> None:
        """Drain the queue into one launch per (family, k) group and
        scatter results.  Runs as a scheduler job on the DHT thread.
        Round 16: the hot-cache probe peels cache hits off the batch
        FIRST (one XOR-compare launch over the whole wave), so a hot
        get never joins the ``[Q]`` lookup launch at all.

        Round 20, ``pipeline_depth >= 2``: launches are dispatched
        asynchronously and queue on ``_inflight``; the only blocking
        wait here is the backpressure bound (a full pipeline drains its
        oldest wave before dispatching the next).  Waves that are
        already ready at the end of the fire (host-scan resolves)
        scatter inline — everything else is left to the drainer job, so
        this fire returns to the runner loop with the device busy."""
        self._job = None
        # round 24 (ISSUE-20): stored puts buffered since the last
        # wave ride THIS fire's single listener_match launch — one
        # coalesced delivery dispatch per wave per listener
        # (runtime/dht.py flush_listener_wave; the deadline job is the
        # idle-node fallback)
        lt = getattr(self._dht, "listener_table", None)
        if lt is not None and lt.pending():
            try:
                self._dht.flush_listener_wave()
            except Exception:
                log.exception("listener wave flush failed")
        if not self._pending:
            return
        batch = list(self._pending)
        self._pending.clear()
        self._m_depth.set(0)
        wf = waterfall.get_profiler()
        if self.pipeline_depth > 1:
            # backpressure: never more than depth waves in flight — the
            # oldest wave's scatter is paid here, while its successors
            # keep the device busy
            if len(self._inflight) >= self.pipeline_depth:
                self.observatory.note_backpressure()
            while len(self._inflight) >= self.pipeline_depth:
                self._drain_one(wf)
        # waterfall (round 19): queue_wait = admission → wave pickup,
        # off the honest enqueue stamp (t_wall, see _Entry) — stamped
        # here, before the cache probe, so a cache-served op still
        # contributes its coalesce tax
        t_pick = _time.time()
        # fill_done edge: the observatory hands back this wave group's
        # fill_start and re-arms for the next (None with the plane off)
        t_fill = self.observatory.take_fill(t_pick)
        if wf.enabled:
            for e in batch:
                wf.observe("queue_wait", max(0.0, t_pick - e.t_wall),
                           exemplar=e.ctx.trace_hex if e.ctx else None)
        cache = getattr(self._dht, "hotcache", None)
        probe_s = 0.0
        n_submitted = len(batch)
        if cache is not None and cache.active():
            # time the probe ONLY when a cache is actually live — a
            # cache-off wave would flood the cache_probe histogram
            # with ~0 samples and bury the real probe's p50
            t_probe = _time.time()
            batch = self._serve_cached(batch)
            probe_s = max(0.0, _time.time() - t_probe)
            if wf.enabled:
                wf.observe("cache_probe", probe_s)
        else:
            batch = self._serve_cached(batch)
        if not batch and n_submitted:
            # the whole wave was served from cache — the device was
            # (correctly) skipped; the idle gap this opens is a
            # cache_served bubble, not starvation
            self.observatory.note_cache_served(t_fill, n_submitted)
        if batch:
            groups: dict = {}
            for e in batch:
                groups.setdefault((e.af, e.k), []).append(e)
            if self.pipeline_depth <= 1:
                for (af, k), entries in groups.items():
                    self._launch(af, k, entries, wf, t_pick, probe_s, t_fill)
                return
            for (af, k), entries in groups.items():
                self._launch_async(af, k, entries, wf, t_pick, probe_s, t_fill)
            # opportunistic same-pump drain: a wave whose handle is
            # already materialized (host-scan resolve — the live
            # protocol regime) scatters now, keeping small-table
            # latency identical to depth 1.  Never blocks.
            while self._inflight and self._inflight[0].handle.ready():
                self._drain_one(wf)
        if self._inflight:
            self._arm_drain(self._dht.scheduler.time())

    def _serve_cached(self, entries: List[_Entry]) -> List[_Entry]:
        """The serve-from-cache fast path (ISSUE-11): ONE batched
        XOR-compare launch (``ops/cache_probe.py``) over the wave's
        targets against the hot-value cache's device id table.  Hits
        on CACHE-ELIGIBLE entries (pure-get refills — ``cache_cb`` set)
        are served host-side values and removed from the wave; misses
        and ineligible entries fall through unchanged.  Served targets
        still feed the keyspace observatory (source="cache") — a
        cache-served key must stay in the hot window, or it would decay
        out, be evicted, and thrash back in."""
        cache = getattr(self._dht, "hotcache", None)
        if cache is None or not cache.active():
            return entries
        eligible = [e.cache_cb is not None for e in entries]
        if not any(eligible):
            return entries
        served = cache.probe_wave([e.target for e in entries], eligible)
        if not any(v is not None for v in served):
            return entries
        ks = getattr(self._dht, "keyspace", None)
        if ks is not None:
            ks.observe_hashes(
                [e.target for e, v in zip(entries, served)
                 if v is not None], source="cache")
        remaining: List[_Entry] = []
        for e, vals in zip(entries, served):
            if vals is None:
                remaining.append(e)
                continue
            try:
                e.cache_cb(vals)
            except Exception:
                log.exception("cache-serve callback failed")
        return remaining

    def _launch(self, af: int, k: int, entries: List[_Entry],
                wf=None, t_pick: "float | None" = None,
                probe_s: float = 0.0,
                t_fill: "float | None" = None) -> None:
        """Depth-1 wave: the exact pre-pipeline launch→block→scatter
        path (``ingest_pipeline_depth=1``, the escape hatch)."""
        reg = telemetry.get_registry()
        if wf is None:
            wf = waterfall.get_profiler()
        t_fire = _time.time()
        # depth-1 lifecycle: device busy exactly for the blocking
        # launch; dispatch and wait are one edge pair here
        seq = self.observatory.on_dispatch(
            t_fill, t_fire, len(entries), af, k, 0, self._reshard_gen())
        with reg.span("dht_ingest_wave_seconds") as sp:
            try:
                results = self._dht.find_closest_nodes_batched(
                    [e.target for e in entries], af, k)
            except Exception:
                log.exception("ingest wave launch failed (af=%d k=%d Q=%d)",
                              af, k, len(entries))
                results = None
        t_avail = _time.time()
        self.observatory.on_device_done(seq, t_avail)
        if results is None:
            entries = self._requeue_failed(entries)
            if not entries:
                # every entry requeued onto a later wave: close THIS
                # wave's lane slices now — no orphan open intervals
                self.observatory.on_scatter_done(seq, _time.time())
                return
            results = [[] for _ in entries]
        shard_t = int(getattr(self._dht, "last_resolve_shard_t", 1) or 1)
        self._scatter(af, k, entries, results, wf, t_pick, probe_s,
                      t_fire, sp.elapsed, shard_t, t_avail, slot=0,
                      obs_seq=seq)

    def _launch_async(self, af: int, k: int, entries: List[_Entry],
                      wf, t_pick: float, probe_s: float,
                      t_fill: "float | None" = None) -> None:
        """Depth-2+ wave: dispatch the ``[Q]`` launch and return with
        the kernel in flight — the scatter belongs to the drainer."""
        t_dispatch = _time.time()
        try:
            handle = self._dht.find_closest_nodes_launch(
                [e.target for e in entries], af, k)
        except Exception:
            log.exception("ingest wave launch failed (af=%d k=%d Q=%d)",
                          af, k, len(entries))
            entries = self._requeue_failed(entries)
            if entries:
                # retries spent: scatter empty honestly, depth-1 style.
                # The dispatch never reached the device, so no device
                # interval is opened (obs_seq=-1: nothing to close).
                self._scatter(af, k, entries, [[] for _ in entries], wf,
                              t_pick, probe_s, t_dispatch, 0.0, 1,
                              _time.time(), slot=len(self._inflight))
            return
        seq = self.observatory.on_dispatch(
            t_fill, t_dispatch, len(entries), af, k,
            len(self._inflight), self._reshard_gen())
        dispatch_s = max(0.0, _time.time() - t_dispatch)
        if wf.enabled:
            # satellite fix (round 22): host-side dispatch cost is its
            # own stage, observed AT LAUNCH — the in-flight window no
            # longer folds into queue_wait or the device stage.  The
            # first (af, k) dispatch carries tracing/lowering cost; the
            # consume-side device_compile split still owns that story.
            wf.observe("dispatch", dispatch_s,
                       exemplar=next((e.ctx.trace_hex for e in entries
                                      if e.ctx is not None), None))
        self._inflight.append(_InflightWave(
            af, k, entries, handle, t_dispatch, dispatch_s, t_pick,
            probe_s, slot=len(self._inflight), seq=seq))
        n = len(self._inflight)
        self._m_inflight.set(n)
        if n > self.inflight_peak:
            self.inflight_peak = n
            self._m_inflight_peak.set(max(n, self._peak_prev))

    # ------------------------------------------------------------- drain
    def _arm_drain(self, t: float) -> None:
        job = self._drain_job
        if job is not None and not job.cancelled:
            if job.time is not None and t < job.time:
                self._drain_job = self._dht.scheduler.edit(job, t)
        else:
            self._drain_job = self._dht.scheduler.add(t, self._drain)

    def _drain(self) -> None:
        """Dedicated drainer step (round 20): scatter wave N−1's
        fan-out OUTSIDE the fire that launches wave N, so host callback
        loops never sit between two launches.  The sole in-flight wave
        is only consumed when its handle is ready — otherwise the host
        stays free to fill the next wave and the poll re-arms one
        deadline out (a fresh fire's backpressure or inline drain may
        well get there first)."""
        self._drain_job = None
        wf = waterfall.get_profiler()
        while self._inflight:
            if len(self._inflight) > 1 or self._inflight[0].handle.ready():
                self._drain_one(wf)
            else:
                self._arm_drain(self._dht.scheduler.time() + self.deadline)
                return

    def _drain_one(self, wf) -> None:
        w = self._inflight.popleft()
        self._m_inflight.set(len(self._inflight))
        t_wait0 = _time.time()
        try:
            results = w.handle.consume()
        except Exception:
            log.exception("ingest wave consume failed (af=%d k=%d Q=%d)",
                          w.af, w.k, len(w.entries))
            results = None
        t_avail = _time.time()
        self.observatory.on_device_done(w.seq, t_avail)
        # the waterfall device stage at consume: the blocking wait
        # actually paid here (device_wait; the host dispatch cost was
        # observed as its own stage at launch — round-22 satellite).
        # Host time the wave spent in flight between pumps is overlap,
        # not device cost — it is visible as the wave span's wall
        # duration instead.  The wave_seconds histogram keeps its
        # round-20 dispatch+wait semantics.
        wait_s = max(0.0, t_avail - t_wait0)
        self._m_wave_s.observe(w.dispatch_s + wait_s)
        entries = w.entries
        if results is None:
            entries = self._requeue_failed(entries)
            if not entries:
                # fully requeued: close this wave's lane slices so the
                # timeline never leaks an orphan open interval
                self.observatory.on_scatter_done(w.seq, _time.time())
                return
            results = [[] for _ in entries]
        self._scatter(w.af, w.k, entries, results, wf, w.t_pick,
                      w.probe_s, w.t_dispatch, wait_s,
                      w.handle.shard_t, t_avail, slot=w.slot,
                      dispatch_s=w.dispatch_s, obs_seq=w.seq)

    def _requeue_failed(self, entries: List[_Entry]) -> List[_Entry]:
        """A failed launch must not fail its carried (already admitted)
        searches on a transient device error: re-queue each entry for
        the next wave, up to _LAUNCH_RETRIES, and return the exhausted
        remainder (to scatter empty — a fresh search with no candidates
        then expires and fails its op honestly: persistent
        infrastructure failure, not backpressure)."""
        telemetry.get_registry().counter(
            "dht_ingest_wave_failures_total").inc()
        # the retry round-trip owns the device-idle gap it opens: the
        # NEXT dispatch's bubble is attributed launch_retry
        self.observatory.note_launch_retry()
        requeue = [e for e in entries if e.retries < _LAUNCH_RETRIES]
        exhausted = [e for e in entries if e.retries >= _LAUNCH_RETRIES]
        if requeue:
            for e in requeue:
                e.retries += 1
            # oldest-first (round-20 satellite fix): retried entries
            # re-join AHEAD of anything submitted while the failed wave
            # was in flight.  Appending them put a newer entry at
            # _pending[0], whose t_enq anchors the deadline trigger
            # (_arm in submit) — silently deferring the oldest op.
            self._pending.extendleft(reversed(requeue))
            self._m_depth.set(len(self._pending))
            self._arm(self._dht.scheduler.time() + self.deadline)
        return exhausted

    def _reshard_gen(self) -> int:
        """Boundary generation currently serving (0 = uniform split) —
        the observatory tags each wave with it so a hot swap between
        waves classifies the idle gap as ``reshard_swap``."""
        rs = getattr(self._dht, "reshard", None)
        if rs is not None and getattr(rs, "layout", None) is not None:
            return int(rs.layout.gen)
        return 0

    def _scatter(self, af: int, k: int, entries: List[_Entry], results,
                 wf, t_pick: "float | None", probe_s: float,
                 t_dispatch: float, dev_elapsed: float, shard_t: int,
                 t_avail: float, slot: int, dispatch_s: float = 0.0,
                 obs_seq: int = -1) -> None:
        """Fan a wave's results out to the carried ops' callbacks, with
        all the per-wave bookkeeping (metrics, keyspace, waterfall
        stages, trace spans) — shared verbatim by the synchronous
        depth-1 launch and the pipelined drain, so the two paths cannot
        diverge.  ``t_avail`` is when results materialized (launch end
        / consume end): the per-op scatter_back slices start there."""
        self.waves += 1
        self._m_waves.inc()
        # keyspace observatory (ISSUE-10): the wave's [Q] target ids
        # feed the device count-min sketch + keyspace histogram in ONE
        # batched scatter-add launch per wave (async dispatch — never
        # blocks the scatter path; buffered stored-key puts ride along)
        ks = getattr(self._dht, "keyspace", None)
        if ks is not None:
            ks.observe_hashes([e.target for e in entries])
        self._m_occupancy.observe(len(entries))
        for e in entries:
            self._m_queue_s.observe(max(0.0, t_dispatch - e.t_wall))
        # shard_t is truth, not config: what the resolve ACTUALLY used —
        # a wave served by the host scan or the churn view reports t=1
        # even when a resolve mesh is configured.  Carried per launch
        # (BatchedResolve.shard_t / last_resolve_shard_t): overlapping
        # waves must not read a shared flag at consume time.
        if shard_t > 1:
            self._m_sharded_waves.inc()
        # waterfall device stage: the first timed launch of an (af, k)
        # group carries XLA compilation — split so a one-time lowering
        # never poisons the serving p99 (host-side bookkeeping only;
        # the launch itself is untouched).  With the pipeline this is
        # observed at CONSUME (the blocking wait; the host dispatch
        # share was observed as the "dispatch" stage at launch —
        # round-22 satellite; "device_launch" remains as a one-release
        # alias of device_wait, see waterfall.STAGE_ALIASES).
        dev_stage = "device_wait"
        if wf.enabled:
            dev_stage = ("device_compile" if wf.first_launch((af, k))
                         else "device_wait")
            wf.observe(dev_stage, dev_elapsed,
                       exemplar=next((e.ctx.trace_hex for e in entries
                                      if e.ctx is not None), None))

        # ISSUE-4 spine: one dht.search.wave span per launch (the
        # ingest-mode sibling of the engine's wave span), each carried
        # op linked to it by a dht.ingest.op child span under the OP'S
        # own trace — a Perfetto load shows which wave served which op
        # and how long the op sat in the queue.  Host-side, after the
        # launch: tracing cannot perturb the kernel.
        tr = tracing.get_tracer()
        wave_ctx = None
        wave_end = t_avail
        if tr.enabled and any(e.ctx is not None for e in entries):
            # round 13: device-cost attrs from the ledger's canonical
            # coalesced-launch entry, with per-device table traffic
            # scaled by 1/t when the resolve ran row-sharded (empty
            # dict until the ledger is computed — a dict lookup on the
            # hot path, same discipline as record_wave's wave_attrs)
            from .. import profiling
            cost = profiling.ingest_wave_attrs(len(entries), shard_t)
            # the span covers dispatch → results materialized (for a
            # pipelined wave that includes the in-flight overlap window
            # — the wall truth); pipeline_slot = waves already in
            # flight when this one launched (0 = head of the pipeline)
            # reshard generation serving this wave (0 = uniform split):
            # across a hot swap the trace shows exactly which waves ran
            # on which boundary generation
            rs = getattr(self._dht, "reshard", None)
            wave_ctx = tr.record(
                "dht.search.wave", t_dispatch,
                max(0.0, t_avail - t_dispatch),
                mode="ingest", occupancy=len(entries), af=af, k=k,
                table_shard_t=shard_t, pipeline_slot=slot,
                reshard_gen=(rs.layout.gen if rs is not None
                             and rs.layout is not None else 0), **cost)
        for e, nodes in zip(entries, results):
            if wave_ctx is not None and e.ctx is not None:
                # span covers submit → scatter, anchored on the entry's
                # own wall stamp so it can never precede its parent op
                tr.record("dht.ingest.op", e.t_wall,
                          max(0.0, wave_end - e.t_wall),
                          parent=e.ctx, kind="internal",
                          op_kind=e.kind, wave_trace=wave_ctx.trace_hex,
                          wave_span=wave_ctx.span_hex,
                          occupancy=len(entries))
            try:
                e.cb(nodes)
            except Exception:
                log.exception("ingest scatter callback failed")
            if wf.enabled:
                # per-op decomposition record: stage sum ≈ end-to-end
                # (admission → this op's scatter returned); rpc_wait
                # overlaps the device stages and is deliberately absent
                t_done = _time.time()
                base = t_pick if t_pick is not None else t_dispatch
                stages = {
                    "queue_wait": max(0.0, base - e.t_wall),
                    "cache_probe": probe_s,
                    dev_stage: dev_elapsed,
                    "scatter_back": max(0.0, t_done - t_avail),
                }
                if dispatch_s > 0.0:
                    stages["dispatch"] = dispatch_s
                wf.record_op(e.kind, stages,
                             end_to_end=max(0.0, t_done - e.t_wall),
                             trace_id=e.ctx.trace_hex if e.ctx else None)
        if wf.enabled:
            # ONE scatter_back observation per wave (the whole fan-out
            # loop) — the per-op slices live in the records above
            wf.observe("scatter_back",
                       max(0.0, _time.time() - t_avail))
        # scatter_done edge: closes the wave's lane slices, linking the
        # timeline record to its dht.search.wave span for Perfetto
        self.observatory.on_scatter_done(
            obs_seq, _time.time(),
            trace=wave_ctx.trace_hex if wave_ctx is not None else "",
            span=wave_ctx.span_hex if wave_ctx is not None else "")

    # ---------------------------------------------------------- inspection
    def frame_tick(self) -> None:
        """History-ring frame hook (round 22): roll the windowed
        in-flight peak (satellite fix — ``dhtmon --window`` should see
        the CURRENT pipeline depth, not a boot-time spike) and push an
        occupancy window checkpoint into the observatory.  The exported
        gauge is max(previous frame, current frame) so it never blinks
        to zero at the frame edge while waves are still in flight."""
        self._peak_prev = self.inflight_peak
        self.inflight_peak = len(self._inflight)
        self._m_inflight_peak.set(
            float(max(self._peak_prev, self.inflight_peak)))
        self.observatory.on_frame()

    def pipeline_snapshot(self) -> dict:
        """Utilization snapshot for ``GET /pipeline`` / the ``pipeline``
        REPL cmd / ``dhtscanner --json``: the observatory's occupancy /
        bubble / overlap ledger plus the builder's pipeline shape."""
        doc = self.observatory.snapshot()
        doc.update({
            "pipeline_depth": self.pipeline_depth,
            "inflight": len(self._inflight),
            "inflight_peak": max(self.inflight_peak, self._peak_prev),
            "queue_depth": len(self._pending),
        })
        return doc

    def snapshot(self) -> dict:
        """JSON-able ingest state for the ops tools (``dhtscanner
        --json`` "ingest" section, the dhtnode REPL ``ingest`` cmd)."""
        occ = self._m_occupancy
        qs = self._m_queue_s
        mean_occ = (occ.sum / occ.count) if occ.count else 0.0
        try:
            shard_t = self._dht.resolve_mesh_t()
        except Exception:
            shard_t = 1
        return {
            "batching": "on" if self.enabled else "off",
            "pipeline_depth": self.pipeline_depth,
            "inflight": len(self._inflight),
            "inflight_peak": max(self.inflight_peak, self._peak_prev),
            "table_shard_t": shard_t,
            "sharded_waves": int(self._m_sharded_waves.value),
            "fill_target": self.fill_target,
            "deadline_s": self.deadline,
            "queue_depth": len(self._pending),
            "queue_max": self.queue_max,
            "waves": self.waves,
            "occupancy_mean": round(mean_occ, 3),
            "occupancy_p50": round(occ.quantile(0.5), 3),
            "occupancy_p95": round(occ.quantile(0.95), 3),
            "queue_seconds_p50": qs.quantile(0.5),
            "queue_seconds_p95": qs.quantile(0.95),
            "sheds": int(sum(c.value for c in self._m_sheds.values())),
        }
