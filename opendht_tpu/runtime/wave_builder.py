"""Continuous-batching ingest: coalesce live lookups into shared waves.

Five rounds of kernel work made the device side of a lookup a ``[Q]``
wave (``find_closest_nodes_batched`` → one lane-padded top-k launch for
*many* targets), but live traffic never fed it one: every proxy/REST
request, UDP op and embedder ``get/put/listen`` reached the table
through a per-search refill — a Q=1 launch paying the full 128-lane
padding tax per op.  Benchmarks batched; the service did not.

This module is the ingest layer that closes that gap (ROADMAP item 2),
the same iteration-level insight that made continuous batching the
serving architecture for LLM engines (Orca-style: admit work
mid-flight, keep launches full, never barrier a wave on its slowest
member):

- :class:`WaveBuilder` owns a bounded admission queue of pending
  closest-node lookups (search refills, from ALL traffic sources — the
  runner op queue, the proxy server, the UDP reply path's search
  stepping).  A wave fires when the queue reaches the **fill target Q**
  or when the oldest entry has waited the **deadline knob** (1–5 ms,
  both ``runtime/config.py`` fields), whichever comes first — one
  ``find_closest_nodes_batched`` launch per (family, k) group, results
  scattered back to each search's callback.  An op that joins after a
  wave departed simply rides the next one at whatever round it is on:
  continuous batching, not stop-and-go batch barriers.
- **Backpressure sheds at admission, never mid-search**: NEW ops are
  refused (``admit``) when the queue exceeds ``ingest_queue_max`` or
  the optional ``ingest_admit_per_sec`` sliding-window quota (the same
  :class:`~opendht_tpu.rate_limiter.RateLimiter` the net engine's
  ingress path uses, and the same counted-drop discipline as its
  ``dht_net_ratelimit_drops_total``) — an admitted search's refills
  are always queued, so backpressure can never fail an in-flight
  search.
- ``ingest_batching="off"`` is the escape hatch: ``submit`` resolves
  synchronously through the identical per-op ``[1]`` launch the
  pre-round-12 path issued — pinned result-equivalent in
  tests/test_wave_builder.py and the burst-ingest CI smoke
  (testing/ingest_smoke.py).
- Observability on the PR-3/PR-4/PR-6 spine: ``dht_ingest_queue_depth``
  gauge, ``dht_ingest_wave_occupancy`` / ``dht_ingest_queue_seconds`` /
  ``dht_ingest_wave_seconds`` histograms, shed/wave/op counters, a
  ``dht.search.wave`` (mode="ingest") trace span per launch with each
  carried op's ``dht.ingest.op`` span linked to it, and the canonical
  launch shape cost-gated from day one (profiling.py
  ``wave_builder_lookup`` ↔ perf_budgets.json).

Threading: the builder lives on the DHT thread like everything else in
``runtime/dht.py`` — submissions come from posted closures, packet
handlers and scheduler jobs, and the wave trigger is itself a scheduler
job, so there are no locks and no re-entrancy (a fill-triggered wave
fires on the *next* scheduler pump, never synchronously inside the
submit that filled it).

Reference mapping: ``DhtRunner::loop_`` (dhtrunner.cpp:387-445) drains
all pending op *closures* onto one thread per pump — coalescing in
time, op by op.  The TPU design deliberately diverges: we coalesce the
ops' *device lookups* onto one launch (coalescing in the lane
dimension), because here the padded launch — not the thread hop — is
the per-op tax.  See PARITY.md "Continuous-batching ingest".
"""

from __future__ import annotations

import logging
import time as _time
from collections import deque
from typing import Callable, List

from .. import telemetry, tracing, waterfall
from ..infohash import InfoHash
from ..rate_limiter import RateLimiter

log = logging.getLogger("opendht_tpu.ingest")

#: failed-launch re-queues per entry before scattering empty (a
#: transient device error retries on later waves; a persistent one
#: fails the carried ops honestly after this many attempts)
_LAUNCH_RETRIES = 2


class _Entry:
    """One queued lookup: target → per-search scatter callback.

    ``t_enq`` is scheduler time (drives the deadline trigger);
    ``t_wall`` is the wall clock at submit — the honest enqueue stamp
    for the time-in-queue histogram and the ``dht.ingest.op`` span.
    The two deliberately differ: the runner drains op closures BEFORE
    ``periodic()`` re-syncs the scheduler clock, so scheduler time at
    submit can be stale by a whole sleep — reconstructing span starts
    from it put a child span seconds before its parent (caught by the
    cross-node assembler's monotonicity check)."""

    __slots__ = ("target", "af", "k", "cb", "t_enq", "t_wall", "ctx",
                 "kind", "retries", "cache_cb")

    def __init__(self, target: InfoHash, af: int, k: int, cb: Callable,
                 t_enq: float, t_wall: float, ctx, kind: str,
                 cache_cb: "Callable | None" = None):
        self.target = target
        self.af = af
        self.k = k
        self.cb = cb
        self.t_enq = t_enq
        self.t_wall = t_wall
        self.ctx = ctx
        self.kind = kind
        self.retries = 0              # failed-launch re-queues so far
        # round 16 (ISSUE-11): non-None marks a CACHE-ELIGIBLE entry (a
        # pure-get refill) — a hot-cache probe hit calls cache_cb(values)
        # and the entry never joins the lookup launch
        self.cache_cb = cache_cb


class WaveBuilder:
    """Fill-or-deadline-triggered aggregator over
    ``Dht.find_closest_nodes_batched`` (see module docstring)."""

    def __init__(self, dht, config):
        self._dht = dht
        self.enabled = getattr(config, "ingest_batching", "on") != "off"
        self.fill_target = max(1, int(
            getattr(config, "ingest_fill_target", 64)))
        self.deadline = float(getattr(config, "ingest_deadline", 0.002))
        self.queue_max = int(getattr(config, "ingest_queue_max", 4096))
        admit_qps = int(getattr(config, "ingest_admit_per_sec", 0) or 0)
        self._admit_limiter = (RateLimiter(admit_qps) if admit_qps > 0
                               else None)
        self._pending: deque = deque()
        self._job = None              # armed scheduler Job or None
        self._exempt = 0              # admission suspended (see exempt())
        self.waves = 0                # launches issued (cheap introspection)

        reg = telemetry.get_registry()
        self._m_depth = reg.gauge("dht_ingest_queue_depth")
        self._m_occupancy = reg.histogram("dht_ingest_wave_occupancy")
        self._m_queue_s = reg.histogram("dht_ingest_queue_seconds")
        self._m_waves = reg.counter("dht_ingest_waves_total")
        # round 13: waves whose resolve ran against the t-sharded table
        # (config.resolve_mesh_t) — the occupancy/latency histograms
        # above cover both modes; this counter says which mode served
        self._m_sharded_waves = reg.counter("dht_ingest_sharded_waves_total")
        self._m_ops = {}              # kind -> counter (cached handles)
        self._m_sheds = {}            # reason -> counter

    # ------------------------------------------------------------ admission
    def exempt(self):
        """Context manager: suspend admission control for internal
        continuations of ALREADY-admitted work — the proxy hot-swap
        re-registering established listeners on the new backend must
        never be shed (the subscription was admitted when it was
        created; dropping it on swap would violate the never-mid-search
        discipline)."""
        import contextlib

        @contextlib.contextmanager
        def _ctx():
            self._exempt += 1
            try:
                yield
            finally:
                self._exempt -= 1
        return _ctx()

    def admit(self, op: str) -> bool:
        """Admission check for a NEW public op (get/put/listen/query).
        False ⇒ the op must be refused *now*, with a counted drop —
        the only place backpressure acts, so a search that got in can
        always finish (its refills bypass this check via
        :meth:`submit`).  With batching off there is no queue to
        protect and every op is admitted (the per-op path's behavior,
        kept result-equivalent)."""
        if not self.enabled or self._exempt:
            return True
        if len(self._pending) >= self.queue_max:
            self._shed(op, "queue_full")
            return False
        if self._admit_limiter is not None and not self._admit_limiter.limit(
                self._dht.scheduler.time()):
            self._shed(op, "rate")
            return False
        return True

    def _shed(self, op: str, reason: str) -> None:
        c = self._m_sheds.get((op, reason))
        if c is None:
            c = self._m_sheds[(op, reason)] = telemetry.get_registry(
            ).counter("dht_ingest_sheds_total", op=op, reason=reason)
        c.inc()
        tr = tracing.get_tracer()
        if tr.enabled:
            tr.event("ingest_shed", op=op, reason=reason,
                     depth=len(self._pending))
        log.debug("ingest shed %s (%s, depth=%d)", op, reason,
                  len(self._pending))

    # ------------------------------------------------------------- ingest
    def submit(self, target: InfoHash, af: int, k: int,
               cb: Callable[[List], None], *, kind: str = "refill",
               cache_cb: "Callable | None" = None) -> None:
        """Queue one closest-``k`` lookup for ``target``; ``cb(nodes)``
        fires from the wave that carries it (immediately, with the
        identical per-op launch, when batching is off).  Never sheds —
        admission already happened at the op boundary.

        ``cache_cb`` (round 16) marks the entry cache-eligible: the
        pre-launch hot-cache probe may serve it values instead of nodes
        (``_serve_cached``), in which case it never joins the launch."""
        if not self.enabled:
            # escape hatch: the per-op [1] launch — the keyspace
            # observatory still sees the target (its surfaces must not
            # go dark when batching is off; results are untouched)
            ks = getattr(self._dht, "keyspace", None)
            if ks is not None:
                ks.observe_hashes([target])
            cb(self._dht.find_closest_nodes_batched([target], af, k)[0])
            return
        now = self._dht.scheduler.time()
        self._pending.append(_Entry(target, af, k, cb, now, _time.time(),
                                    tracing.current(), kind, cache_cb))
        depth = len(self._pending)
        self._m_depth.set(depth)
        c = self._m_ops.get(kind)
        if c is None:
            c = self._m_ops[kind] = telemetry.get_registry().counter(
                "dht_ingest_ops_total", kind=kind)
        c.inc()
        # fill target ⇒ pull the trigger to *now* (the next scheduler
        # pump — never synchronously inside a submit, see module doc);
        # otherwise make sure a deadline trigger covers the new oldest
        self._arm(now if depth >= self.fill_target
                  else self._pending[0].t_enq + self.deadline)

    def _arm(self, t: float) -> None:
        job = self._job
        if job is not None and not job.cancelled:
            # job.time is None while the scheduler has the job in its
            # CURRENT due sweep (run() nulls the time before executing,
            # scheduler.py) — a submit() from a sibling due job lands
            # here; the wave fires later this same sweep and drains the
            # new entry, so nothing to reschedule
            if job.time is not None and t < job.time:
                self._job = self._dht.scheduler.edit(job, t)
        else:
            self._job = self._dht.scheduler.add(t, self._fire)

    def pending(self) -> int:
        return len(self._pending)

    # --------------------------------------------------------------- waves
    def _fire(self) -> None:
        """Drain the queue into one launch per (family, k) group and
        scatter results.  Runs as a scheduler job on the DHT thread.
        Round 16: the hot-cache probe peels cache hits off the batch
        FIRST (one XOR-compare launch over the whole wave), so a hot
        get never joins the ``[Q]`` lookup launch at all."""
        self._job = None
        if not self._pending:
            return
        batch = list(self._pending)
        self._pending.clear()
        self._m_depth.set(0)
        # waterfall (round 19): queue_wait = admission → wave pickup,
        # off the honest enqueue stamp (t_wall, see _Entry) — stamped
        # here, before the cache probe, so a cache-served op still
        # contributes its coalesce tax
        wf = waterfall.get_profiler()
        t_pick = _time.time()
        if wf.enabled:
            for e in batch:
                wf.observe("queue_wait", max(0.0, t_pick - e.t_wall),
                           exemplar=e.ctx.trace_hex if e.ctx else None)
        cache = getattr(self._dht, "hotcache", None)
        probe_s = 0.0
        if cache is not None and cache.active():
            # time the probe ONLY when a cache is actually live — a
            # cache-off wave would flood the cache_probe histogram
            # with ~0 samples and bury the real probe's p50
            t_probe = _time.time()
            batch = self._serve_cached(batch)
            probe_s = max(0.0, _time.time() - t_probe)
            if wf.enabled:
                wf.observe("cache_probe", probe_s)
        else:
            batch = self._serve_cached(batch)
        if not batch:
            return
        groups: dict = {}
        for e in batch:
            groups.setdefault((e.af, e.k), []).append(e)
        for (af, k), entries in groups.items():
            self._launch(af, k, entries, wf, t_pick, probe_s)

    def _serve_cached(self, entries: List[_Entry]) -> List[_Entry]:
        """The serve-from-cache fast path (ISSUE-11): ONE batched
        XOR-compare launch (``ops/cache_probe.py``) over the wave's
        targets against the hot-value cache's device id table.  Hits
        on CACHE-ELIGIBLE entries (pure-get refills — ``cache_cb`` set)
        are served host-side values and removed from the wave; misses
        and ineligible entries fall through unchanged.  Served targets
        still feed the keyspace observatory (source="cache") — a
        cache-served key must stay in the hot window, or it would decay
        out, be evicted, and thrash back in."""
        cache = getattr(self._dht, "hotcache", None)
        if cache is None or not cache.active():
            return entries
        eligible = [e.cache_cb is not None for e in entries]
        if not any(eligible):
            return entries
        served = cache.probe_wave([e.target for e in entries], eligible)
        if not any(v is not None for v in served):
            return entries
        ks = getattr(self._dht, "keyspace", None)
        if ks is not None:
            ks.observe_hashes(
                [e.target for e, v in zip(entries, served)
                 if v is not None], source="cache")
        remaining: List[_Entry] = []
        for e, vals in zip(entries, served):
            if vals is None:
                remaining.append(e)
                continue
            try:
                e.cache_cb(vals)
            except Exception:
                log.exception("cache-serve callback failed")
        return remaining

    def _launch(self, af: int, k: int, entries: List[_Entry],
                wf=None, t_pick: "float | None" = None,
                probe_s: float = 0.0) -> None:
        reg = telemetry.get_registry()
        if wf is None:
            wf = waterfall.get_profiler()
        t_fire = _time.time()
        with reg.span("dht_ingest_wave_seconds") as sp:
            try:
                results = self._dht.find_closest_nodes_batched(
                    [e.target for e in entries], af, k)
            except Exception:
                log.exception("ingest wave launch failed (af=%d k=%d Q=%d)",
                              af, k, len(entries))
                results = None
        t_launch_end = _time.time()
        if results is None:
            # a failed launch must not fail its carried (already
            # admitted) searches on a transient device error: re-queue
            # each entry for the next wave, up to _LAUNCH_RETRIES.  Only
            # after the retries are spent does an entry scatter empty —
            # a fresh search with no candidates then expires and fails
            # its op honestly (persistent infrastructure failure, not
            # backpressure).
            reg.counter("dht_ingest_wave_failures_total").inc()
            requeue = [e for e in entries if e.retries < _LAUNCH_RETRIES]
            exhausted = [e for e in entries if e.retries >= _LAUNCH_RETRIES]
            for e in requeue:
                e.retries += 1
                self._pending.append(e)
            if requeue:
                self._m_depth.set(len(self._pending))
                self._arm(self._dht.scheduler.time() + self.deadline)
            if not exhausted:
                return
            entries = exhausted
            results = [[] for _ in entries]
        self.waves += 1
        self._m_waves.inc()
        # keyspace observatory (ISSUE-10): the wave's [Q] target ids
        # feed the device count-min sketch + keyspace histogram in ONE
        # batched scatter-add launch per wave (async dispatch — never
        # blocks the scatter path; buffered stored-key puts ride along)
        ks = getattr(self._dht, "keyspace", None)
        if ks is not None:
            ks.observe_hashes([e.target for e in entries])
        self._m_occupancy.observe(len(entries))
        for e in entries:
            self._m_queue_s.observe(max(0.0, t_fire - e.t_wall))
        # truth, not config: what the resolve ACTUALLY used — a wave
        # served by the host scan or the churn view reports t=1 even
        # when a resolve mesh is configured (Dht sets this right after
        # the table call, same thread)
        shard_t = int(getattr(self._dht, "last_resolve_shard_t", 1) or 1)
        if shard_t > 1:
            self._m_sharded_waves.inc()
        # waterfall device stage: the first timed launch of an (af, k)
        # group carries XLA compilation — split so a one-time lowering
        # never poisons the serving p99 (host-side bookkeeping only;
        # the launch itself is untouched)
        dev_stage = "device_launch"
        if wf.enabled:
            dev_stage = ("device_compile" if wf.first_launch((af, k))
                         else "device_launch")
            wf.observe(dev_stage, sp.elapsed,
                       exemplar=next((e.ctx.trace_hex for e in entries
                                      if e.ctx is not None), None))

        # ISSUE-4 spine: one dht.search.wave span per launch (the
        # ingest-mode sibling of the engine's wave span), each carried
        # op linked to it by a dht.ingest.op child span under the OP'S
        # own trace — a Perfetto load shows which wave served which op
        # and how long the op sat in the queue.  Host-side, after the
        # launch: tracing cannot perturb the kernel.
        tr = tracing.get_tracer()
        wave_ctx = None
        wave_end = t_fire + sp.elapsed
        if tr.enabled and any(e.ctx is not None for e in entries):
            # round 13: device-cost attrs from the ledger's canonical
            # coalesced-launch entry, with per-device table traffic
            # scaled by 1/t when the resolve ran row-sharded (empty
            # dict until the ledger is computed — a dict lookup on the
            # hot path, same discipline as record_wave's wave_attrs)
            from .. import profiling
            cost = profiling.ingest_wave_attrs(len(entries), shard_t)
            wave_ctx = tr.record(
                "dht.search.wave", t_fire, sp.elapsed,
                mode="ingest", occupancy=len(entries), af=af, k=k,
                table_shard_t=shard_t, **cost)
        for e, nodes in zip(entries, results):
            if wave_ctx is not None and e.ctx is not None:
                # span covers submit → scatter, anchored on the entry's
                # own wall stamp so it can never precede its parent op
                tr.record("dht.ingest.op", e.t_wall,
                          max(0.0, wave_end - e.t_wall),
                          parent=e.ctx, kind="internal",
                          op_kind=e.kind, wave_trace=wave_ctx.trace_hex,
                          wave_span=wave_ctx.span_hex,
                          occupancy=len(entries))
            try:
                e.cb(nodes)
            except Exception:
                log.exception("ingest scatter callback failed")
            if wf.enabled:
                # per-op decomposition record: stage sum ≈ end-to-end
                # (admission → this op's scatter returned); rpc_wait
                # overlaps the device stages and is deliberately absent
                t_done = _time.time()
                base = t_pick if t_pick is not None else t_fire
                wf.record_op(e.kind, {
                    "queue_wait": max(0.0, base - e.t_wall),
                    "cache_probe": probe_s,
                    dev_stage: sp.elapsed,
                    "scatter_back": max(0.0, t_done - t_launch_end),
                }, end_to_end=max(0.0, t_done - e.t_wall),
                    trace_id=e.ctx.trace_hex if e.ctx else None)
        if wf.enabled:
            # ONE scatter_back observation per wave (the whole fan-out
            # loop) — the per-op slices live in the records above
            wf.observe("scatter_back",
                       max(0.0, _time.time() - t_launch_end))

    # ---------------------------------------------------------- inspection
    def snapshot(self) -> dict:
        """JSON-able ingest state for the ops tools (``dhtscanner
        --json`` "ingest" section, the dhtnode REPL ``ingest`` cmd)."""
        occ = self._m_occupancy
        qs = self._m_queue_s
        mean_occ = (occ.sum / occ.count) if occ.count else 0.0
        try:
            shard_t = self._dht.resolve_mesh_t()
        except Exception:
            shard_t = 1
        return {
            "batching": "on" if self.enabled else "off",
            "table_shard_t": shard_t,
            "sharded_waves": int(self._m_sharded_waves.value),
            "fill_target": self.fill_target,
            "deadline_s": self.deadline,
            "queue_depth": len(self._pending),
            "queue_max": self.queue_max,
            "waves": self.waves,
            "occupancy_mean": round(mean_occ, 3),
            "occupancy_p50": round(occ.quantile(0.5), 3),
            "occupancy_p95": round(occ.quantile(0.95), 3),
            "queue_seconds_p50": qs.quantile(0.5),
            "queue_seconds_p95": qs.quantile(0.95),
            "sheds": int(sum(c.value for c in self._m_sheds.values())),
        }
