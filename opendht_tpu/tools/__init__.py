"""CLI tools (↔ reference tools/): dhtnode interactive node/daemon,
dhtchat minimal IM, dhtscanner keyspace census, plus shared argv/identity
helpers (↔ tools/tools_common.h)."""


def force_cpu_jax() -> None:
    """Pin JAX to the CPU backend (host tools must never grab the
    single-client TPU tunnel; accelerator init would also stall the
    protocol thread).  Lives HERE — not in tools.common, which eagerly
    imports the crypto-backed runner stack — so crypto-free callers
    (the virtual cluster harness, testing/benchmark.py) share the one
    pinning recipe."""
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
