"""Wire-compatibility checker against a LIVE node (round-4 verdict
ask #8 — "keep the interop door cheap").

Points the scripted golden exchanges at a real ``host:port`` over UDP
and reports pass/fail per check.  Against the repo's own ``dhtnode``
this is a live self-test (``--self-test`` spins one up in-process); the
day a reference C++ dhtnode (/root/reference/tools/dhtnode.cpp:104-460)
is reachable, the SURVEY §7 stage-4 acceptance is::

    python -m opendht_tpu.tools.compat_check <host> <port>

Checks (requester side of the reference wire format,
/root/reference/src/network_engine.cpp:677-1305):

1. ping        → reply with the peer's 20-byte id, tid matched
2. find_node   → compact n4 node blob (26 B triples)
3. get         → write token issued (+ closest nodes)
4. listen      → listen confirmation on a fresh socket id
5. put         → value-announced ack echoing the value id
6. get (again) → the stored value round-trips bit-exact
7. put >600 B  → fragmented announce (value parts) acked
8. get (big)   → fragmented values reassembled bit-exact
9. put w/ forged token → protocol error 401 (UNAUTHORIZED)
10. refresh unknown vid → protocol error 404 (NOT_FOUND)
11. traced ping → a query carrying the ``tr`` trace-context key is
    answered like any untraced one (ISSUE-4 wire compat; the first
    10 checks send NO ``tr``, so they double as the pre-trace-peer →
    tracing-peer direction)
12. unknown-keys ping → a raw packet with hostile unknown top-level
    keys (including an oversized fake trace blob) still gets a reply,
    and the reply echoes none of the unknown bytes

Every check is also a behavioral assertion from the conversation-golden
tier (tests/test_wire_conversations.py) — this tool is those flows
unfrozen and aimed at a socket.
"""

from __future__ import annotations

import argparse
import secrets
import select
import socket
import sys
import time

from .. import tracing
from ..core.value import Query, Value
from ..infohash import InfoHash
from ..net.engine import (DhtProtocolException, EngineCallbacks,
                          NetworkEngine)
from ..net.parsed_message import ParsedMessage, pack_tid
from ..scheduler import Scheduler
from ..sockaddr import SockAddr
from ..utils import pack_msg

N_CHECKS = 12


class LiveChecker:
    """One UDP socket + one NetworkEngine driven synchronously."""

    def __init__(self, host: str, port: int, network: int = 0,
                 timeout: float = 4.0):
        self.peer = SockAddr.resolve(host, port)[0]
        fam = self.peer.family
        self.sock = socket.socket(fam, socket.SOCK_DGRAM)
        self.sock.bind(("::" if fam == socket.AF_INET6 else "0.0.0.0", 0))
        self.sock.setblocking(False)
        self.timeout = timeout
        self.errors: list = []
        cbs = EngineCallbacks()
        cbs.on_error = lambda req, e: self.errors.append(e.code)
        self.engine = NetworkEngine(
            InfoHash.get_random(), network,
            lambda data, dst: self.sock.sendto(
                data, (str(dst.ip), dst.port)) and 0,
            Scheduler(), cbs, is_client=True)
        self.node = self.engine.cache.get_node(
            InfoHash(), self.peer, time.monotonic(), confirm=False)

    def pump(self, done) -> bool:
        """Deliver traffic + retries until ``done()`` or timeout."""
        deadline = time.monotonic() + self.timeout
        while time.monotonic() < deadline:
            if done():
                return True
            self.engine.scheduler.run()
            r, _, _ = select.select([self.sock], [], [], 0.05)
            if r:
                try:
                    data, addr = self.sock.recvfrom(64 * 1024)
                except OSError:
                    continue
                self.engine.process_message(
                    data, SockAddr(addr[0], addr[1]))
        return done()

    def relearn_node(self, peer_id: InfoHash):
        """After the ping reply names the peer, use the interned node."""
        self.node = self.engine.cache.get_node(
            peer_id, self.peer, time.monotonic(), confirm=True)

    def close(self):
        self.sock.close()


def run_checks(host: str, port: int, network: int = 0,
               timeout: float = 4.0, verbose: bool = True) -> list:
    """Returns [(name, ok, detail)] for all 10 checks."""
    c = LiveChecker(host, port, network, timeout)
    results: list = []

    def step(name, ok, detail=""):
        results.append((name, bool(ok), detail))
        if verbose:
            print(f"  [{'ok' if ok else 'FAIL'}] {name}"
                  + (f" — {detail}" if detail else ""), flush=True)

    try:
        # 1. ping (anonymous bootstrap request — learns the peer id)
        box: dict = {}
        c.engine.send_ping(c.node, on_done=lambda r, a: box.update(done=r))
        ok = c.pump(lambda: "done" in box)
        peer_id = box["done"].node.id if ok else InfoHash()
        step("ping", ok and len(bytes(peer_id)) == 20,
             f"peer id {peer_id}" if ok else "no reply")
        if not ok:
            return results
        c.relearn_node(peer_id)

        # 2. find_node
        box.clear()
        c.engine.send_find_node(c.node, InfoHash.get_random(), want=1,
                                on_done=lambda r, a: box.update(a=a))
        ok = c.pump(lambda: "a" in box)
        step("find_node", ok, f"{len(box['a'].nodes4)} v4 nodes"
             if ok else "no reply")

        # 3. get → token
        h = InfoHash.get("compat-check-" + secrets.token_hex(4))
        box.clear()
        c.engine.send_get_values(c.node, h, Query(), want=1,
                                 on_done=lambda r, a: box.update(a=a))
        ok = c.pump(lambda: "a" in box)
        token = box["a"].ntoken if ok else b""
        step("get/token", ok and len(token) > 0,
             f"token {len(token)} B" if ok else "no reply")

        # 4. listen
        box.clear()
        got_push: list = []
        c.engine.send_listen(c.node, h, Query(), token, None,
                             on_done=lambda r, a: box.update(a=a),
                             socket_cb=lambda n, m: got_push.append(m))
        ok = c.pump(lambda: "a" in box)
        step("listen", ok, "" if ok else "no confirmation")

        # 5. put (small value)
        payload = b"compat-check-payload-" + secrets.token_hex(8).encode()
        v = Value(payload, value_id=7)
        box.clear()
        c.engine.send_announce_value(c.node, h, v, time.time(), token,
                                     on_done=lambda r, a: box.update(a=a))
        ok = c.pump(lambda: "a" in box)
        step("put", ok and box.get("a") and box["a"].vid == 7,
             f"vid {box['a'].vid}" if ok else "no ack")

        # 6. get → value round-trip
        box.clear()
        c.engine.send_get_values(c.node, h, Query(), want=1,
                                 on_done=lambda r, a: box.update(a=a))
        ok = c.pump(lambda: "a" in box)
        vals = box["a"].values if ok else []
        step("get/value", ok and any(x.data == payload for x in vals),
             f"{len(vals)} values" if ok else "no reply")

        # 7. big (fragmented) put
        big = Value(bytes(range(256)) * 11, value_id=8)      # >600 B packed
        box.clear()
        c.engine.send_announce_value(c.node, h, big, time.time(), token,
                                     on_done=lambda r, a: box.update(a=a))
        ok = c.pump(lambda: "a" in box)
        step("put/fragmented", ok and box.get("a") and box["a"].vid == 8,
             "" if ok else "no ack")

        # 8. get → fragmented value reassembled
        box.clear()
        c.engine.send_get_values(c.node, h, Query(), want=1,
                                 on_done=lambda r, a: box.update(a=a))
        ok = c.pump(lambda: "a" in box)
        vals = box["a"].values if ok else []
        step("get/fragmented", ok and any(x.data == big.data for x in vals),
             f"{len(vals)} values" if ok else "no reply")

        # 9. forged token → 401
        c.errors.clear()
        c.engine.send_announce_value(c.node, h, Value(b"x", value_id=9),
                                     time.time(), b"forged-token",
                                     on_done=lambda r, a: None)
        ok = c.pump(lambda: DhtProtocolException.UNAUTHORIZED in c.errors)
        step("put/forged-token→401", ok, "" if ok else
             f"errors seen: {c.errors}")

        # 10. refresh unknown hash → 404
        c.errors.clear()
        c.engine.send_refresh_value(c.node, InfoHash.get_random(), 123,
                                    token, on_done=lambda r, a: None)
        ok = c.pump(lambda: DhtProtocolException.NOT_FOUND in c.errors)
        step("refresh/unknown→404", ok, "" if ok else
             f"errors seen: {c.errors}")

        # 11. traced ping: the optional tr key must not change behavior
        box.clear()
        root = tracing.TraceContext.new_root()
        with tracing.activate(root):
            c.engine.send_ping(c.node,
                               on_done=lambda r, a: box.update(done=r))
        ok = c.pump(lambda: "done" in box)
        step("ping/trace-ctx", ok, "" if ok else "no reply")

        # 12. unknown top-level keys (incl. an oversized hostile trace
        # blob) parse cleanly on the peer and never echo back.  Blob
        # sized to fit one UDP datagram under the node's 1500 B recv
        # MTU — the multi-KB versions live in tests/test_wire_fuzz.py,
        # which feeds the parser in-process without a datagram limit.
        blob = b"\xaa" * 600
        tid12 = 0x7A7A7A7A
        raw = pack_msg({
            "a": {"id": bytes(c.engine.myid)}, "q": "ping",
            "t": pack_tid(tid12), "y": "q", "v": "RNG1",
            "zz_future_key": blob, "tr": blob[:256],
        })
        c.sock.sendto(raw, (str(c.peer.ip), c.peer.port))
        reply = None
        deadline = time.monotonic() + c.timeout
        while reply is None and time.monotonic() < deadline:
            r, _, _ = select.select([c.sock], [], [], 0.05)
            if not r:
                continue
            try:
                data, _addr = c.sock.recvfrom(64 * 1024)
            except OSError:
                continue
            try:
                pm = ParsedMessage.from_bytes(data)
            except Exception:
                continue
            if pm.tid == tid12:
                reply = data
        ok = reply is not None and blob[:64] not in reply
        step("ping/unknown-keys", ok,
             f"reply {len(reply)} B, no echo" if ok else
             ("blob echoed!" if reply else "no reply"))
    finally:
        c.close()
    return results


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Run the scripted wire-compat exchanges against a "
                    "live DHT node")
    p.add_argument("host", nargs="?", default="127.0.0.1")
    p.add_argument("port", nargs="?", type=int, default=4222)
    p.add_argument("-n", "--network", type=int, default=0)
    p.add_argument("--timeout", type=float, default=4.0)
    p.add_argument("--self-test", action="store_true",
                   help="spin up this package's own node in-process and "
                        "check against it")
    args = p.parse_args(argv)

    runner = None
    host, port = args.host, args.port
    if args.self_test:
        from ..runtime.runner import DhtRunner
        runner = DhtRunner()
        runner.run(0)
        host, port = "127.0.0.1", runner.get_bound_port()
        print(f"self-test node on {host}:{port}")

    try:
        print(f"compat check vs {host}:{port}")
        results = run_checks(host, port, args.network, args.timeout)
    finally:
        if runner is not None:
            runner.shutdown()
            runner.join()
    n_ok = sum(1 for _, ok, _ in results if ok)
    print(f"{n_ok}/{len(results)} checks passed")
    return 0 if n_ok == len(results) == N_CHECKS else 1


if __name__ == "__main__":
    sys.exit(main())
