"""dhtchat: minimal IM over the DHT (↔ reference tools/dhtchat.cpp).

Joins a chat room (any string, hashed to a key), listens for signed
``ImMessage`` values on it, and putSigned's what you type.  Usage::

    python -m opendht_tpu.tools.dhtchat -b host:port <room>
"""

from __future__ import annotations

import random
import sys
import time

from ..infohash import InfoHash
from ..core.default_types import IM_MESSAGE_TYPE, ImMessage
from .common import make_arg_parser, print_node_info, setup_node


def main(argv=None) -> int:
    p = make_arg_parser("OpenDHT-TPU chat")
    p.add_argument("room", help="chat room name")
    args = p.parse_args(argv)
    if not args.identity and not args.save_identity:
        args.identity = True        # chat requires a signing identity
    node = setup_node(args)
    print_node_info(node)
    room = InfoHash.get("room:" + args.room)
    my_id = node.get_id()
    start = time.time()

    def on_msg(values, expired) -> bool:
        # (dhtchat.cpp:55-77): show only fresh messages from others
        for v in values:
            if expired or v.type != IM_MESSAGE_TYPE.id:
                continue
            try:
                m = ImMessage.from_value(v)
            except Exception:
                continue
            if m.from_id == my_id or m.date < start * 1000 - 60_000:
                continue
            who = str(m.from_id)[:8] if m.from_id else "???"
            print("\r%s at %s: %s\n> " % (who, time.strftime(
                "%H:%M:%S", time.localtime(m.date / 1000)), m.msg),
                end="", flush=True)
        return True

    tok = node.listen(room, on_msg, ImMessage.get_filter())
    try:
        # runner.listen returns a Future resolving to the runner token;
        # 0 = shed at ingest admission (round 12) — warn instead of
        # silently joining a room that will never deliver messages
        if hasattr(tok, "result") and not tok.result(10.0):
            print("warning: listen shed by ingest backpressure — "
                  "incoming messages will not be delivered")
    except Exception:
        pass
    print("Joined room %s as %s (empty line to quit)" % (args.room, my_id))
    try:
        while True:
            line = input("> ")
            if not line:
                break
            msg = ImMessage(random.getrandbits(64), line,
                            int(time.time() * 1000))
            node.put_signed(room, msg.to_value(),
                            lambda ok, ns: ok or print("(send failed)"))
    except (EOFError, KeyboardInterrupt):
        print()
    finally:
        node.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
