"""dhtmon: cluster health invariants CLI (ISSUE-9).

Scrapes every listed node's ``GET /healthz`` + ``GET /stats`` (the
proxy surfaces of opendht_tpu/health.py) and checks the cluster
invariants with exit-code thresholds, so one command drives CI gates,
soak monitors and pager policy:

- per-node verdicts (``--require-ready`` fails unless every node's
  /healthz returns 200);
- global lookup success rate from the summed op-outcome counters
  (``--min-success R``);
- cluster op-latency percentiles from the merged
  ``dht_op_seconds_bucket`` series, via the ONE ``--alert PCT=SEC``
  grammar shared with testing/network_monitor.py (health.parse_alerts);
- the batched replica-coverage probe (``--min-coverage R``) when
  invoked programmatically with in-process runners
  (:func:`run_checks` ``runners=``; the probe needs the cluster's
  stores — testing/health_monitor.replica_coverage), resolving every
  sampled key's true closest-8 in ONE batched device launch.

Exit codes: 0 = all invariants hold; 1 = an invariant violated;
2 = usage / scrape error.

Usage::

    python -m opendht_tpu.tools.dhtmon --nodes 127.0.0.1:8080 \\
        --min-success 0.99 --alert p95=2.5 --require-ready [--json]
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
from typing import List, Optional

from ..health import (parse_alerts, percentile_breaches,
                      quantile_from_cumulative)
from ..testing import health_monitor as hm
from ..waterfall import STAGE_ALIASES, STAGES

#: ``dht_stage_seconds_bucket{stage="queue_wait",le="0.001"}`` →
#: (stage, le) — both label orders, like health_monitor._BUCKET_RE
_STAGE_BUCKET_RE = re.compile(
    r'^dht_stage_seconds_bucket\{le="([^"]+)",stage="([^"]+)"\}$'
    r'|^dht_stage_seconds_bucket\{stage="([^"]+)",le="([^"]+)"\}$')


def _stage_p95s(series: dict) -> dict:
    """Per-stage p95 off one node's scraped ``dht_stage_seconds``
    buckets (+Inf dropped; a never-observed stage exports no finite
    buckets and is simply absent — unknown, never a violation)."""
    per: dict = {}
    for name, v in series.items():
        m = _STAGE_BUCKET_RE.match(name)
        if not m:
            continue
        le_s, stage = ((m.group(1), m.group(2)) if m.group(1) is not None
                       else (m.group(4), m.group(3)))
        if le_s == "+Inf":
            continue
        per.setdefault(stage, []).append((float(le_s), v))
    return {stage: quantile_from_cumulative(sorted(pairs), 0.95)
            for stage, pairs in per.items()}


def run_checks(endpoints: List[str] = (), runners=(), alerts=None,
               min_success: Optional[float] = None,
               min_coverage: Optional[float] = None,
               require_ready: bool = False, op: str = "get",
               sample_max: int = 64, k: int = 8, mesh=None,
               window: float = 0.0, since: Optional[float] = None,
               max_imbalance: Optional[float] = None,
               min_cache_hit: Optional[float] = None,
               max_stage: Optional[dict] = None,
               min_occupancy: Optional[float] = None,
               max_peer_fail: Optional[float] = None,
               max_listener_lag: Optional[float] = None) -> tuple:
    """Scrape + evaluate; returns ``(violations, doc)`` where ``doc``
    is the JSON-able cluster report and ``violations`` is a list of
    human-readable invariant failures (empty = healthy).

    ``window > 0`` evaluates the success/latency invariants over a
    WINDOW.  Since round 17 the PREFERRED source is each node's
    ``GET /history`` endpoint (the flight data recorder's retained
    delta frames: no second scrape, no wait — the window already
    happened); only when a node does not export history does the check
    fall back to the legacy scrape-diff-scrape (scrape, wait
    ``window`` seconds, scrape again, diff the cumulative series).
    Both sources feed the SAME invariant code (``lookup_success`` /
    ``cluster_quantile`` over one summed series map — pinned equal in
    tests/test_history.py).  ``since`` is the strict form: evaluate
    over the last ``since`` seconds of HISTORY ONLY, raising when any
    node lacks the endpoint (no silent wait) — the soak/CI gate form.
    The default (0) reads the since-boot cumulative ratio — right for
    a CI smoke's bounded lifetime, wrong for a week-old soak, where
    lifetime counters both hide a fresh outage and remember a
    recovered one forever (review finding).
    ONLY the success/latency invariants window: readiness, the
    replica-coverage probe and the imbalance gauge are point-in-time
    by nature, so when no windowed invariant is requested
    (``min_success`` unset and no ``alerts``) the history/baseline
    scrape and any wait are skipped entirely (ISSUE-10 satellite — a
    coverage-only ``--window`` run used to scrape every node twice
    for nothing).

    ``max_imbalance`` gates the round-15 keyspace observatory's
    per-shard load balance: the worst node's ``dht_shard_imbalance``
    gauge (max/mean per-shard windowed traffic; -1 = unknown, never a
    violation) must not exceed it.

    ``min_cache_hit`` gates the round-16 hot-key serving cache: the
    worst node's ``dht_cache_hit_ratio`` gauge (windowed hits /
    eligible probes) must not drop below it — the SAME unknown
    contract as ``max_imbalance``: a -1/absent gauge (cache disabled,
    dark, or no probes in the window) never violates.

    ``max_stage`` ({stage: seconds}) gates the round-19 latency
    waterfall: the worst node's per-stage p95 off its scraped
    ``dht_stage_seconds`` buckets must not exceed the stage's
    threshold.  Per-node like the other gauge gates (one slow node
    must not hide inside a cluster merge); a never-observed stage is
    unknown and never violates.

    ``min_occupancy`` gates the round-22 pipeline observatory: the
    worst node's ``dht_pipeline_occupancy`` gauge (windowed fraction
    of wall clock with >= 1 wave in flight on the device) must not
    drop below it — the SAME unknown contract as the other gauge
    gates: a -1/absent gauge (observatory off, or no window closed
    yet) never violates.

    ``max_peer_fail`` gates the round-23 per-peer ledger: the worst
    single link's ``dht_peer_fail_ratio{peer=}`` gauge (expired /
    finished requests of one peer, published only past
    ``Config.peers.min_signal_events`` requests) must not exceed it
    across any node — the per-LINK view next to the cluster-wide
    timeout ratio, so one dying link cannot hide inside healthy
    aggregates.  The SAME unknown contract as ``--max-imbalance``: a
    -1/absent gauge (ledger off, peer evicted, or too little traffic
    to judge) never violates.

    ``max_listener_lag`` gates the round-24 listener table: the worst
    node's ``dht_listener_lag_p95`` gauge (windowed p95 of store-time
    -> coalesced-delivery-dispatch lag through the wave-batched match,
    seconds) must not exceed it — a drain stall or a fattened flush
    deadline shows up here before subscribers notice.  The SAME
    unknown contract as ``--max-imbalance``: a -1/absent gauge (table
    off, batching off, dark, or no delivery in the window) never
    violates."""
    alerts = alerts or {}
    violations: List[str] = []
    baseline = None
    hist_series = None
    window_source = None
    windowed = min_success is not None or bool(alerts)
    if since is not None and not since > 0:
        # a non-positive --since would silently fall through to the
        # since-boot cumulative evaluation — the exact failure mode
        # --since exists to prevent; refuse loudly (exit 2 in main)
        raise ValueError("--since must be a positive window (got %g)"
                         % since)
    if since is not None and not endpoints:
        # runners-only invocations have no GET /history to read; a
        # silent skip would report a windowed gate passed when nothing
        # was evaluated (review finding)
        raise ValueError("--since requires proxy endpoints exporting "
                         "GET /history")
    win = since if since is not None else window
    if windowed and endpoints and win > 0:
        # round 17: the history endpoint IS the window — no second
        # scrape, no wait.  All-or-nothing across nodes: a mixed
        # cluster would double-count traffic if half the series were
        # windowed deltas and half cumulative diffs.
        hists = []
        for ep in endpoints:
            h = hm.scrape_history(ep, win)
            if h is None:
                hists = None
                break
            hists.append(h)
        if hists is not None:
            hist_series = hm.merge_history_series(hists)
            window_source = "history"
        elif since is not None:
            raise RuntimeError(
                "--since requires every node to export GET /history "
                "(the round-17 flight data recorder)")
        else:
            baseline = hm.merge_series([hm.scrape_node(ep)
                                        for ep in endpoints])
            time.sleep(window)
            window_source = "scrape-diff"
    scrapes = []
    for ep in endpoints:
        scrapes.append(hm.scrape_node(ep))
    doc: dict = {
        "nodes": [{"endpoint": s["endpoint"], "ready": s["ready"],
                   "verdict": s["verdict"]} for s in scrapes],
        "window_s": (win or None) if windowed else None,
        "window_source": window_source,
    }
    if require_ready:
        for s in scrapes:
            if not s["ready"]:
                violations.append("node %s not ready (verdict %s)"
                                  % (s["endpoint"], s["verdict"]))
    series = hm.merge_series(scrapes) if scrapes else {}
    if hist_series is not None:
        # the recorder's summed frame deltas have the same shape as a
        # scrape diff (history.frames_to_series) — the invariant code
        # below cannot tell the sources apart
        series = hist_series
    elif baseline is not None:
        # cumulative counters and cumulative-by-le buckets both diff
        # cleanly; only the summed counter/bucket series are read below
        series = {key: max(v - baseline.get(key, 0.0), 0.0)
                  for key, v in series.items()}
    ls = hm.lookup_success(series, op=op) if series else None
    doc["lookup_success"] = (
        {"ratio": ls[0], "ops": ls[1]} if ls is not None else None)
    if min_success is not None and ls is not None and ls[0] < min_success:
        violations.append(
            "lookup success %.4f < %.4f over %d %s ops"
            % (ls[0], min_success, int(ls[1]), op))
    if alerts and series:
        doc["latency"] = {
            "p%g" % p: hm.cluster_quantile(series, op, p / 100.0)
            for p in sorted(alerts)}
        for pct, v, thr in percentile_breaches(
                lambda q: hm.cluster_quantile(series, op, q), alerts):
            violations.append("cluster %s p%g %.3fs exceeds %.3fs"
                              % (op, pct, v, thr))
    if max_imbalance is not None and scrapes:
        # per-node, NOT merged: imbalance ratios don't sum — the gate
        # is "no node's keyspace is lopsided", so take the worst node
        # (-1/absent = observatory unknown, never a violation)
        per_node = []
        for s in scrapes:
            vals = [v for name, v in s["series"].items()
                    if name.startswith("dht_shard_imbalance") and v >= 0]
            per_node.append({"endpoint": s["endpoint"],
                             "imbalance": max(vals) if vals else None})
        known = [p["imbalance"] for p in per_node
                 if p["imbalance"] is not None]
        worst = max(known) if known else None
        doc["shard_imbalance"] = {"max": worst, "per_node": per_node}
        if worst is not None and worst > max_imbalance:
            violations.append(
                "shard imbalance %.3f exceeds %.3f (worst node %s)"
                % (worst, max_imbalance,
                   max(per_node, key=lambda p: p["imbalance"] or -1)
                   ["endpoint"]))
    if min_cache_hit is not None and scrapes:
        # per-node, worst = MIN: the gate is "every node's hot traffic
        # is actually being served from its cache" — -1/absent =
        # unknown (disabled / no probe window), never a violation
        per_node = []
        for s in scrapes:
            vals = [v for name, v in s["series"].items()
                    if name.startswith("dht_cache_hit_ratio") and v >= 0]
            per_node.append({"endpoint": s["endpoint"],
                             "hit_ratio": min(vals) if vals else None})
        known = [p["hit_ratio"] for p in per_node
                 if p["hit_ratio"] is not None]
        worst = min(known) if known else None
        doc["cache_hit"] = {"min": worst, "per_node": per_node}
        if worst is not None and worst < min_cache_hit:
            violations.append(
                "cache hit ratio %.3f below %.3f (worst node %s)"
                % (worst, min_cache_hit,
                   min(per_node,
                       key=lambda p: p["hit_ratio"]
                       if p["hit_ratio"] is not None else 2.0)
                   ["endpoint"]))
    if min_occupancy is not None and scrapes:
        # per-node, worst = MIN: the gate is "every node's device is
        # actually being kept busy by its pipeline" — -1/absent =
        # unknown (observatory off / no closed window), never a
        # violation, matching the other gauge gates
        per_node = []
        for s in scrapes:
            vals = [v for name, v in s["series"].items()
                    if name.startswith("dht_pipeline_occupancy")
                    and v >= 0]
            per_node.append({"endpoint": s["endpoint"],
                             "occupancy": min(vals) if vals else None})
        known = [p["occupancy"] for p in per_node
                 if p["occupancy"] is not None]
        worst = min(known) if known else None
        doc["pipeline_occupancy"] = {"min": worst, "per_node": per_node}
        if worst is not None and worst < min_occupancy:
            violations.append(
                "pipeline occupancy %.4f below %.4f (worst node %s)"
                % (worst, min_occupancy,
                   min(per_node,
                       key=lambda p: p["occupancy"]
                       if p["occupancy"] is not None else 2.0)
                   ["endpoint"]))
    if max_peer_fail is not None and scrapes:
        # per-node, worst = MAX over that node's per-peer fail-ratio
        # gauges: the gate is "no single link is silently dying" —
        # -1/absent = unknown (ledger off / evicted peer / below
        # min_signal_events), never a violation, matching the other
        # gauge gates.  The gauge name prefix matches every peer label
        # series of dht_peer_fail_ratio.
        per_node = []
        for s in scrapes:
            vals = [v for name, v in s["series"].items()
                    if name.startswith("dht_peer_fail_ratio")
                    and v >= 0]
            per_node.append({"endpoint": s["endpoint"],
                             "peer_fail": max(vals) if vals else None})
        known = [p["peer_fail"] for p in per_node
                 if p["peer_fail"] is not None]
        worst = max(known) if known else None
        doc["peer_fail"] = {"max": worst, "per_node": per_node}
        if worst is not None and worst > max_peer_fail:
            violations.append(
                "peer fail ratio %.3f exceeds %.3f (worst node %s)"
                % (worst, max_peer_fail,
                   max(per_node,
                       key=lambda p: p["peer_fail"]
                       if p["peer_fail"] is not None else -1.0)
                   ["endpoint"]))
    if max_listener_lag is not None and scrapes:
        # per-node, worst = MAX: the gate is "no node's wave-batched
        # listen/push delivery is lagging subscribers" — -1/absent =
        # unknown (table off, batching off, dark, or no delivery
        # window), never a violation, matching the other gauge gates
        per_node = []
        for s in scrapes:
            vals = [v for name, v in s["series"].items()
                    if name.startswith("dht_listener_lag_p95")
                    and v >= 0]
            per_node.append({"endpoint": s["endpoint"],
                             "listener_lag": max(vals) if vals else None})
        known = [p["listener_lag"] for p in per_node
                 if p["listener_lag"] is not None]
        worst = max(known) if known else None
        doc["listener_lag"] = {"max": worst, "per_node": per_node}
        if worst is not None and worst > max_listener_lag:
            violations.append(
                "listener delivery lag p95 %.4fs exceeds %.4fs "
                "(worst node %s)"
                % (worst, max_listener_lag,
                   max(per_node,
                       key=lambda p: p["listener_lag"]
                       if p["listener_lag"] is not None else -1.0)
                   ["endpoint"]))
    if max_stage and scrapes:
        # per-node, worst = MAX p95 per stage: the gate is "no node's
        # serving stage blew its latency budget" — a stage with no
        # finite buckets (never observed) is unknown, never a violation
        per_node = [{"endpoint": s["endpoint"],
                     "p95": _stage_p95s(s["series"])} for s in scrapes]
        worst: dict = {}
        for stage, thr in sorted(max_stage.items()):
            vals = [(p["p95"][stage], p["endpoint"]) for p in per_node
                    if p["p95"].get(stage) is not None]
            w = max(vals) if vals else None
            worst[stage] = {"p95": w[0] if w else None, "threshold": thr}
            if w is not None and w[0] > thr:
                violations.append(
                    "stage %s p95 %.4fs exceeds %.4fs (worst node %s)"
                    % (stage, w[0], thr, w[1]))
        doc["stages"] = {"worst": worst, "per_node": per_node}
    if runners:
        cov = hm.replica_coverage(runners, sample_max=sample_max, k=k,
                                  mesh=mesh)
        doc["replica_coverage"] = cov
        if min_coverage is not None and cov["keys"] and \
                cov["mean_coverage"] < min_coverage:
            violations.append(
                "replica coverage %.3f < %.3f over %d keys "
                "(min per-key %.3f)"
                % (cov["mean_coverage"], min_coverage, cov["keys"],
                   cov["min_coverage"]))
    doc["violations"] = violations
    return violations, doc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="cluster health invariants monitor (exit-code "
                    "thresholds for CI and soak)")
    p.add_argument("--nodes", action="append", default=[],
                   metavar="HOST:PORT[,HOST:PORT...]",
                   help="proxy endpoints to scrape (repeatable or "
                        "comma-separated)")
    p.add_argument("--alert", action="append", default=[],
                   metavar="PCT=SEC",
                   help="fail when the cluster-merged op latency "
                        "percentile exceeds SEC (e.g. --alert p95=2.5; "
                        "the same grammar as network_monitor)")
    p.add_argument("--min-success", type=float, default=None,
                   metavar="R",
                   help="fail when the global lookup success ratio "
                        "drops below R (e.g. 0.99)")
    p.add_argument("--require-ready", action="store_true",
                   help="fail unless every node's GET /healthz is 200")
    p.add_argument("--op", default="get",
                   help="op family for the success/latency invariants "
                        "(default: get)")
    p.add_argument("--window", type=float, default=0.0, metavar="SEC",
                   help="evaluate the SUCCESS/LATENCY invariants over "
                        "a SEC-second window instead of the "
                        "since-boot cumulative — use for long-lived "
                        "clusters, where lifetime ratios hide fresh "
                        "outages and remember recovered ones.  Reads "
                        "each node's GET /history frames (round-17 "
                        "flight data recorder: no wait) when every "
                        "node exports them, falling back to scrape-"
                        "wait-scrape-diff otherwise.  Readiness, the "
                        "replica-coverage probe and --max-imbalance "
                        "are point-in-time and unaffected; with no "
                        "windowed invariant requested the extra "
                        "scrapes are skipped entirely")
    p.add_argument("--since", type=float, default=None, metavar="SEC",
                   help="like --window, but STRICTLY from the nodes' "
                        "GET /history frames over the last SEC "
                        "seconds — exits 2 when any node lacks the "
                        "recorder instead of silently waiting out a "
                        "scrape-diff window (the soak/CI gate form)")
    p.add_argument("--max-imbalance", type=float, default=None,
                   metavar="R",
                   help="fail when any node's keyspace shard-load "
                        "imbalance (dht_shard_imbalance: max/mean "
                        "per-shard windowed traffic from the count-min "
                        "observatory) exceeds R — 1.0 is perfect "
                        "balance, the shard count is a single-shard "
                        "flood; unknown (no traffic window) never "
                        "violates")
    p.add_argument("--min-cache-hit", type=float, default=None,
                   metavar="R",
                   help="fail when any node's hot-key cache hit ratio "
                        "(dht_cache_hit_ratio: windowed hits / eligible "
                        "probes from the round-16 serving cache) drops "
                        "below R — unknown (-1/absent: cache disabled "
                        "or no probe window) never violates, matching "
                        "the --max-imbalance contract")
    p.add_argument("--min-occupancy", type=float, default=None,
                   metavar="R",
                   help="fail when any node's pipeline device "
                        "occupancy (dht_pipeline_occupancy: windowed "
                        "fraction of wall clock with >=1 wave in "
                        "flight, from the round-22 observatory) drops "
                        "below R — unknown (-1/absent: observatory "
                        "off or no closed window) never violates, "
                        "matching the --min-cache-hit contract")
    p.add_argument("--max-peer-fail", type=float, default=None,
                   metavar="R",
                   help="fail when any single link's fail ratio "
                        "(dht_peer_fail_ratio{peer=}: expired / "
                        "finished requests to one peer, from the "
                        "round-23 per-peer ledger) exceeds R on any "
                        "node — unknown (-1/absent: ledger off, peer "
                        "evicted, or below Config.peers."
                        "min_signal_events requests) never violates, "
                        "matching the --max-imbalance contract")
    p.add_argument("--max-listener-lag", type=float, default=None,
                   metavar="SEC",
                   help="fail when any node's listener delivery lag "
                        "p95 (dht_listener_lag_p95: windowed store->"
                        "coalesced-dispatch lag through the round-24 "
                        "wave-batched match, seconds) exceeds SEC — "
                        "unknown (-1/absent: table off, batching off, "
                        "dark, or no delivery window) never violates, "
                        "matching the --max-imbalance contract")
    p.add_argument("--max-stage", action="append", default=[],
                   metavar="STAGE=SEC",
                   help="fail when any node's p95 for a round-19 "
                        "waterfall stage (dht_stage_seconds: "
                        "queue_wait, cache_probe, device_compile, "
                        "dispatch, device_wait, scatter_back, "
                        "rpc_wait) exceeds SEC (repeatable, e.g. "
                        "--max-stage device_wait=0.25); "
                        "device_launch is accepted as a one-release "
                        "alias of device_wait (round-22 stage split); "
                        "a never-observed stage is unknown and never "
                        "violates")
    p.add_argument("--json", action="store_true",
                   help="emit the full cluster report as one JSON doc")
    args = p.parse_args(argv)
    try:
        alerts = parse_alerts(args.alert)
    except ValueError as e:
        print("dhtmon:", e, file=sys.stderr)
        return 2
    max_stage: dict = {}
    for spec in args.max_stage:
        stage, eq, sec = spec.partition("=")
        # one-release compatibility (round 22): --max-stage
        # device_launch=... resolves to the canonical device_wait
        # stage instead of silently failing to match anything
        stage = STAGE_ALIASES.get(stage, stage)
        try:
            if not eq or stage not in STAGES:
                raise ValueError
            max_stage[stage] = float(sec)
        except ValueError:
            print("dhtmon: invalid --max-stage %r (want STAGE=SEC, "
                  "STAGE one of %s)" % (spec, ", ".join(STAGES)),
                  file=sys.stderr)
            return 2
    endpoints = [ep for spec in args.nodes for ep in spec.split(",") if ep]
    if not endpoints:
        print("dhtmon: no --nodes given", file=sys.stderr)
        return 2
    try:
        violations, doc = run_checks(
            endpoints, alerts=alerts, min_success=args.min_success,
            require_ready=args.require_ready, op=args.op,
            window=args.window, since=args.since,
            max_imbalance=args.max_imbalance,
            min_cache_hit=args.min_cache_hit,
            max_stage=max_stage or None,
            min_occupancy=args.min_occupancy,
            max_peer_fail=args.max_peer_fail,
            max_listener_lag=args.max_listener_lag)
    except Exception as e:
        print("dhtmon: scrape failed: %s" % e, file=sys.stderr)
        return 2
    if args.json:
        json.dump(doc, sys.stdout)
        print()
    else:
        for n in doc["nodes"]:
            print("node %s: %s%s" % (n["endpoint"], n["verdict"],
                                     "" if n["ready"] else " (NOT READY)"))
        if doc.get("window_source"):
            print("window: %gs via %s" % (doc["window_s"],
                                          doc["window_source"]))
        ls = doc.get("lookup_success")
        if ls:
            print("lookup success: %.4f over %d ops"
                  % (ls["ratio"], int(ls["ops"])))
        for name, v in sorted((doc.get("latency") or {}).items()):
            print("cluster %s %s: %s" % (
                args.op, name, "%.3fs" % v if v is not None else "n/a"))
        imb = doc.get("shard_imbalance")
        if imb:
            print("shard imbalance: %s (worst node)" % (
                "%.3f" % imb["max"] if imb["max"] is not None
                else "unknown"))
        ch = doc.get("cache_hit")
        if ch:
            print("cache hit ratio: %s (worst node)" % (
                "%.3f" % ch["min"] if ch["min"] is not None
                else "unknown"))
        po = doc.get("pipeline_occupancy")
        if po:
            print("pipeline occupancy: %s (worst node)" % (
                "%.4f" % po["min"] if po["min"] is not None
                else "unknown"))
        pf = doc.get("peer_fail")
        if pf:
            print("peer fail ratio: %s (worst link)" % (
                "%.3f" % pf["max"] if pf["max"] is not None
                else "unknown"))
        ll = doc.get("listener_lag")
        if ll:
            print("listener lag p95: %s (worst node)" % (
                "%.4fs" % ll["max"] if ll["max"] is not None
                else "unknown"))
        for stage, w in sorted((doc.get("stages") or {})
                               .get("worst", {}).items()):
            print("stage %s p95: %s (max %.4fs, worst node)" % (
                stage, "%.4fs" % w["p95"] if w["p95"] is not None
                else "unknown", w["threshold"]))
    for v in violations:
        print("ALERT:", v, file=sys.stderr)
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
