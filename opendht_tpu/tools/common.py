"""Shared CLI plumbing for the tools (↔ reference tools/tools_common.h:
argv parsing — port, bootstrap, netid, identity, proxy, logging — plus
identity save/load and the node-info dump)."""

from __future__ import annotations

import argparse
import logging
import os
import sys
import time
from typing import Optional, Tuple

from ..infohash import InfoHash
from ..runtime.config import Config
from ..runtime.runner import DhtRunner, RunnerConfig
from ..utils import lazy_module

# crypto is a CALL-time dependency only (identity generate/load/save):
# lazy so the CLI tools import — and the identity-less REPL/scanner
# paths run — without the `cryptography` wheel (same pattern as
# runtime/runner.py, ISSUE-2 satellite)
crypto = lazy_module("opendht_tpu.crypto")


# canonical definition lives in the (crypto-free) package __init__ so
# the virtual harness can use it without this module's runner imports;
# re-exported here for the CLI tools and back-compat
from . import force_cpu_jax  # noqa: F401,E402


def make_arg_parser(description: str) -> argparse.ArgumentParser:
    """(↔ parseArgs, tools_common.h:120-210)"""
    p = argparse.ArgumentParser(description=description)
    p.add_argument("-p", "--port", type=int, default=0,
                   help="UDP port to bind (default: any)")
    p.add_argument("-b", "--bootstrap", default="",
                   help="bootstrap node host[:port]")
    p.add_argument("-n", "--network", type=int, default=0,
                   help="network id (partitions the DHT)")
    p.add_argument("-i", "--identity", action="store_true",
                   help="generate a cryptographic identity")
    p.add_argument("--save-identity", default="",
                   help="path prefix to save/load the identity")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="enable debug logging")
    p.add_argument("--proxyserver", type=int, default=0,
                   help="run a REST proxy server on this port")
    p.add_argument("--proxyclient", default="",
                   help="use a REST proxy at host:port instead of UDP")
    p.add_argument("--tpu", action="store_true",
                   help="let JAX pick the accelerator backend (default: "
                        "force CPU — a CLI node's tables are small, and "
                        "first-time accelerator init would stall the "
                        "protocol thread)")
    return p


def parse_bootstrap(spec: str) -> Optional[Tuple[str, int]]:
    """host[:port], [v6]:port, or bare IPv6 literal → (host, port)."""
    if not spec:
        return None
    if spec.startswith("["):                    # [2001:db8::1]:4222
        host, _, rest = spec[1:].partition("]")
        port = rest.lstrip(":")
    elif spec.count(":") == 1:                  # host:port
        host, _, port = spec.partition(":")
    else:                                       # bare host or IPv6 literal
        host, port = spec, ""
    return host, int(port or 4222)


def load_identity(path_prefix: str) -> Optional[crypto.Identity]:
    """(↔ loadIdentity, tools_common.h:216-245)"""
    key_path, crt_path = path_prefix + ".pem", path_prefix + ".crt"
    if not (os.path.exists(key_path) and os.path.exists(crt_path)):
        return None
    with open(key_path, "rb") as f:
        key = crypto.PrivateKey(f.read())
    with open(crt_path, "rb") as f:
        cert = crypto.Certificate(f.read())
    return crypto.Identity(key, cert)


def save_identity(ident: crypto.Identity, path_prefix: str) -> None:
    """(↔ saveIdentity, tools_common.h:247-259)"""
    with open(path_prefix + ".pem", "wb") as f:
        f.write(ident.first.serialize())
    with open(path_prefix + ".crt", "wb") as f:
        f.write(ident.second.pack())


def setup_node(args) -> DhtRunner:
    """Build + start a runner from parsed args (↔ dhtnode main,
    tools/dhtnode.cpp:480-545)."""
    if args.verbose:
        logging.basicConfig(level=logging.DEBUG)
    if not getattr(args, "tpu", False):
        force_cpu_jax()
    ident = None
    if args.save_identity:
        ident = load_identity(args.save_identity)
    if ident is None and (args.identity or args.save_identity):
        ident = crypto.generate_identity("dhtnode", key_length=2048)
        if args.save_identity:
            save_identity(ident, args.save_identity)
    conf = RunnerConfig(dht_config=Config(network=args.network),
                        identity=ident)
    node = DhtRunner()
    node.run(args.port, conf)
    bs = parse_bootstrap(args.bootstrap)
    if bs:
        node.bootstrap(*bs)
    if args.proxyclient:
        node.enable_proxy(args.proxyclient)
    return node


def save_state(node: DhtRunner, path: str) -> None:
    """Persist good nodes + stored values to a msgpack file (↔ the
    reference's exportNodes/exportValues persistence, SURVEY.md §5
    checkpoint/resume; dhtnode identity/state save in tools_common.h)."""
    from ..utils import pack_msg
    state = {"nodes": node.export_nodes(), "values": node.export_values()}
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(pack_msg(state))
    os.replace(tmp, path)


def load_state(node: DhtRunner, path: str) -> Tuple[int, int]:
    """Re-insert persisted nodes (bootstrap without ping, insertNode
    semantics dht.h:109-119) and values (clamped creation dates).
    Returns (n_nodes, n_keys)."""
    from ..sockaddr import SockAddr as _SA
    from ..utils import unpack_msg
    with open(path, "rb") as f:
        state = unpack_msg(f.read())
    inserted = 0
    for n in state.get("nodes", []):
        try:
            # after a msgpack round-trip addr can only be compact bytes;
            # anything else is corrupt and would fail asynchronously on
            # the DHT thread, so skip it here
            if not isinstance(n["addr"], (bytes, bytearray)):
                continue
            node.bootstrap_node(InfoHash(n["id"]),
                                _SA.from_compact(n["addr"]))
            inserted += 1
        except Exception:
            continue
    values = state.get("values", [])
    node.import_values(values)
    return inserted, len(values)


def print_node_info(node: DhtRunner) -> None:
    """(↔ print_node_info, tools_common.h:97-107)"""
    print("OpenDHT-TPU node %s" % node.get_node_id())
    if node.get_id():
        print("Public key ID %s" % node.get_id())
    print("Bound to port %d" % node.get_bound_port())


def print_node_stats(node: DhtRunner) -> None:
    import socket
    for name, af in (("IPv4", socket.AF_INET), ("IPv6", socket.AF_INET6)):
        try:
            st = node.get_node_stats(af)
        except Exception:
            continue
        print("%s stats: %s" % (name, st.to_dict()))
