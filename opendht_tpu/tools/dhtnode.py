"""dhtnode: interactive CLI node / daemon (↔ reference tools/dhtnode.cpp).

REPL ops (cmd_loop, dhtnode.cpp:104-460):
    h                      help
    x / q / quit           exit
    ll                     print routing tables, searches and storage logs
    lr                     routing tables log
    ls [hash]              searches log
    la                     storage (announced values) log
    b <host[:port]>        bootstrap
    cc                     simulate connectivity change
    g <hash>               get
    l <hash>               listen (prints updates; 'cl <token>' to stop)
    cl <token>             cancel listen
    p <hash> <text>        put
    pp <hash> <text>       permanent put
    cpp <hash> <vid>       cancel permanent put
    s <hash> <text>        put signed
    e <hash> <to> <text>   put encrypted to recipient hash
    q? <hash> <where>      query (e.g. q? <hash> id=42)
    il <name> <key> [vid]  index: insert (key as field=value)
    ii <name> <key>        index: lookup
    stats [prom]           unified telemetry (JSON snapshot; 'prom' =
                           Prometheus text, same registry as GET /stats)
    kernels [measure]      kernel cost ledger: per-kernel XLA cost model
                           (flops / bytes accessed / HBM footprint) at
                           the canonical shapes ci/perf_gate.py budgets;
                           'measure' adds one timed canonical launch per
                           kernel + roofline attribution vs the platform
                           peaks.  Exports dht_kernel_* gauges to the
                           same registry GET /stats serves
    ingest                 continuous-batching ingest state (round 12):
                           queue depth, wave occupancy p50/p95 + mean,
                           time-in-queue p50/p95, waves fired, sheds —
                           the wave builder's live coalescing health
    trace [id|chrome [f]]  distributed tracing: no arg = recent trace
                           ids in the ring; '<trace id>' = that trace's
                           span tree; 'chrome [file]' = Perfetto/Chrome
                           trace-event dump (stdout or file)
    health                 node health verdict (healthy | degraded |
                           unhealthy) with per-signal and per-SLO
                           burn-rate attribution — the same JSON the
                           proxy serves on GET /healthz
    keyspace [json]        keyspace traffic observatory (round 15):
                           heavy-hitter top-K off the device count-min
                           sketch (windowed estimates, hot flags),
                           occupied histogram bins, per-shard load
                           attribution + imbalance ratio — the same
                           data the proxy serves on GET /keyspace;
                           'json' dumps the full snapshot (incl. the
                           256-bin histogram)
    reshard [json]         load-aware resharding (round 21): installed
                           boundary generation + solved edges,
                           tick/swap/skip counters (skips labeled
                           below-threshold / hysteresis / cooldown),
                           sustain latch age and post-swap refolded
                           imbalance — the same data the proxy serves
                           on GET /reshard
    profile [json|folded]  per-op latency waterfall (round 19): per-
                           stage p50/p95/p99 (queue_wait, cache_probe,
                           device_compile/launch, scatter_back,
                           rpc_wait), the stage budgets and the live
                           OPEN-bound comparison — the same data the
                           proxy serves on GET /profile; 'json' dumps
                           the full snapshot (incl. per-op records +
                           bucket exemplars), 'folded' prints
                           flamegraph-shaped folded stacks
    pipeline [json]        pipeline utilization observatory (round
                           22): windowed device occupancy, per-cause
                           device-idle bubble attribution (queue_empty
                           / fill_slow / drain_backpressure /
                           launch_retry / reshard_swap / cache_served),
                           measured fill∥device overlap ratio and the
                           pipeline shape — the same data the proxy
                           serves on GET /pipeline (?fmt=trace there
                           for the Perfetto lane export)
    peers [json]           per-peer network observatory (round 23):
                           per-peer srtt/rttvar + adaptive RTO,
                           request outcome counts, attempt timeouts,
                           spurious retransmits, bytes by message
                           type and good<->dubious<->expired flap
                           transitions — the same data the proxy
                           serves on GET /peers; 'json' dumps the
                           full snapshot
    listeners [json]       device-resident listener table (round 24):
                           occupancy/overflow/tombstones, buffered
                           values awaiting the next wave's batched
                           match, delivery-lag p95 and the soonest-
                           expiring entries — the same data the proxy
                           serves on GET /listeners; 'json' dumps the
                           full snapshot
    cache [json]           hot-key serving cache (round 16): occupancy,
                           per-entry hit counts, windowed hit ratio,
                           invalidation/eviction totals and the
                           widened (closest-16) hot set — the same
                           data the proxy serves on GET /cache; 'json'
                           dumps the full snapshot
    dump [n] [name]        flight-recorder dump: last n (default 40)
                           structured events + span count (the
                           reference's dumpTables analogue); a
                           non-numeric arg filters by event/span name
                           substring (e.g. 'dump health')
    bundle [file]          post-mortem black-box bundle (round 17):
                           last-N history frames + flight-recorder
                           ring + kernel ledger + keyspace/cache
                           snapshots + health report in one JSON
                           artifact — the same document the proxy
                           serves on GET /debug/bundle; with a file
                           arg the bundle is written there, otherwise
                           a summary prints (auto-captured bundles
                           from past unhealthy transitions listed)
    stt <port>             start REST proxy server
    stp                    stop REST proxy server
    pst <host:port>        switch backend to a REST proxy (client)
    psp                    switch back to the UDP backend
    info                   node id, port, stats
"""

from __future__ import annotations

import shlex
import socket
import sys
import time

from ..infohash import InfoHash
from ..core.value import Value
from .common import (make_arg_parser, parse_bootstrap, print_node_info,
                     print_node_stats, setup_node)


def to_hash(word: str) -> InfoHash:
    """40-hex-char args are hashes; anything else is hashed as a key
    (the reference requires strict hex — dhtnode.cpp:131-138 — this is a
    usability extension)."""
    if len(word) == 2 * InfoHash.HASH_LEN:
        try:
            return InfoHash(word)
        except Exception:
            pass
    return InfoHash.get(word)

HELP = __doc__


def _value_str(v: Value) -> str:
    flags = []
    if v.is_signed():
        flags.append("signed")
    if v.is_encrypted():
        flags.append("encrypted")
    body = v.data.decode("utf-8", "replace") if not v.is_encrypted() else "<cypher>"
    return "Value[id:%x%s%s] %r" % (
        v.id, " " if flags else "", ",".join(flags), body)


def cmd_loop(node, args) -> None:            # noqa: C901 — REPL dispatch
    """(↔ cmd_loop, dhtnode.cpp:104-460)"""
    from ..indexation.pht import Pht

    proxy_server = None
    indexes = {}
    listen_tokens = {}

    print("(type 'h' for help)")
    while True:
        try:
            line = input("> ")
        except (EOFError, KeyboardInterrupt):
            print()
            break
        try:
            words = shlex.split(line)
        except ValueError as e:
            print("parse error: %s" % e)
            continue
        if not words:
            continue
        op, rest = words[0], words[1:]
        try:
            if op in ("x", "q", "exit", "quit"):
                break
            elif op in ("h", "help"):
                print(HELP)
            elif op == "info":
                print_node_info(node)
                print_node_stats(node)
            elif op == "stats":
                # the unified telemetry registry (ISSUE-3): same data
                # the proxy serves on GET /stats
                if rest and rest[0] in ("prom", "prometheus"):
                    from ..telemetry import get_registry
                    print(get_registry().prometheus(), end="")
                else:
                    import json as _json
                    print(_json.dumps(node.get_metrics(), indent=2,
                                      sort_keys=True))
            elif op == "kernels":
                # kernel cost ledger (ISSUE-6): lowers each shipped
                # kernel at its canonical shape on first use (seconds),
                # cached for the process; 'measure' adds a timed launch
                # + roofline % of platform peak
                from .. import profiling
                led = profiling.get_ledger()
                if rest and rest[0] == "measure":
                    led.measure()
                else:
                    led.compute()
                led.export_to_registry()
                entries = led.snapshot()
                print("%-28s %s" % ("kernel",
                                    "  MFLOP  MB-accessed  MB-hbm"))
                for name in sorted(entries):
                    e = entries[name]
                    if "error" in e:
                        print("%-28s ERROR %s" % (name, e["error"]))
                        continue
                    line = "%-28s %7.2f %12.2f %7.2f" % (
                        name, e["flops"] / 1e6,
                        e["bytes_accessed"] / 1e6, e["hbm_bytes"] / 1e6)
                    if "live_p50_s" in e:
                        line += "  live p50 %.3f ms (n=%d)" % (
                            e["live_p50_s"] * 1e3, e["live_count"])
                    rl = e.get("roofline")
                    if rl:
                        line += "  %.3f ms -> %s-bound, %.1f%% HBM peak" \
                            % (e["measured_s"] * 1e3, rl["bound"],
                               rl["hbm_pct_of_peak"])
                    print(line)
                print("%d kernels; budgets gated by ci/perf_gate.py "
                      "(perf_budgets.json)" % len(entries))
            elif op == "ingest":
                # continuous-batching ingest health (round 12): the
                # wave builder's snapshot — same numbers dhtscanner
                # --json reports under "ingest" and the proxy exports
                # as dht_ingest_* series
                try:
                    snap = node._dht.wave_builder.snapshot()
                except AttributeError:
                    print("ingest state unavailable (proxy backend?)")
                    continue
                print("batching %s  fill_target %d  deadline %.1f ms  "
                      "queue %d/%d" % (
                          snap["batching"], snap["fill_target"],
                          snap["deadline_s"] * 1e3,
                          snap["queue_depth"], snap["queue_max"]))
                print("pipeline depth %d  in-flight %d (peak %d)"
                      % (snap.get("pipeline_depth", 1),
                         snap.get("inflight", 0),
                         snap.get("inflight_peak", 0)))
                print("waves %d  occupancy mean %.2f p50 %.1f p95 %.1f"
                      % (snap["waves"], snap["occupancy_mean"],
                         snap["occupancy_p50"], snap["occupancy_p95"]))
                print("time-in-queue p50 %.3f ms  p95 %.3f ms  sheds %d"
                      % (snap["queue_seconds_p50"] * 1e3,
                         snap["queue_seconds_p95"] * 1e3, snap["sheds"]))
            elif op == "trace":
                import json as _json
                from .. import tracing
                from ..testing.trace_assembler import assemble_trace
                tr = tracing.get_tracer()
                if rest and rest[0] == "chrome":
                    dump = tracing.to_chrome_trace(tr.records())
                    if len(rest) > 1:
                        with open(rest[1], "w") as fh:
                            _json.dump(dump, fh)
                        print("%d trace events -> %s (load in "
                              "ui.perfetto.dev)" % (
                                  len(dump["traceEvents"]), rest[1]))
                    else:
                        print(_json.dumps(dump))
                elif rest:
                    tree = assemble_trace([tr], rest[0])
                    print(_json.dumps(tree, indent=2, sort_keys=True))
                else:
                    seen = {}
                    for s in tr.spans():
                        seen.setdefault(s["trace_id"], [0, s["name"]])
                        seen[s["trace_id"]][0] += 1
                    for tid_, (cnt, name) in list(seen.items())[-20:]:
                        print("  %s  %3d spans  (%s)" % (tid_, cnt, name))
                    print("%d trace(s) in the ring" % len(seen))
            elif op == "health":
                # the node health verdict (ISSUE-9): same report the
                # proxy serves on GET /healthz
                import json as _json
                rep = node.get_health()
                print(_json.dumps(rep, indent=2, sort_keys=True))
                print("verdict: %s%s" % (
                    rep.get("verdict", "unknown"),
                    " (causes: %s)" % ", ".join(rep["causes"])
                    if rep.get("causes") else ""))
            elif op == "keyspace":
                # keyspace traffic observatory (ISSUE-10): same
                # snapshot the proxy serves on GET /keyspace
                import json as _json
                snap = node.get_keyspace()
                if rest and rest[0] == "json":
                    print(_json.dumps(snap, indent=2, sort_keys=True))
                elif not snap.get("enabled"):
                    print("keyspace observatory disabled")
                else:
                    print("window %.0f ids (%d lifetime)  occupied bins "
                          "%d/%d  candidates %d" % (
                              snap["window_total"], snap["observed_total"],
                              snap["occupied_bins"], snap["hist_bins"],
                              snap["candidates"]))
                    sh = snap["shards"]
                    print("shards: %s%d  loads %s  imbalance %s" % (
                        "virtual " if sh["virtual"] else "t=",
                        sh["n"] if sh["virtual"] else sh["t"],
                        sh["loads"],
                        sh["imbalance"] if sh["imbalance"] is not None
                        else "unknown"))
                    for t_ in snap["top"]:
                        print("  %s%s  est %d  share %.1f%%" % (
                            t_["key"], "  HOT" if t_["hot"] else "",
                            t_["estimate"], t_["share"] * 100))
                    if not snap["top"]:
                        print("  (no traffic observed yet)")
            elif op == "reshard":
                # load-aware resharding (ISSUE-17): same snapshot the
                # proxy serves on GET /reshard
                import json as _json
                snap = node.get_reshard()
                if rest and rest[0] == "json":
                    print(_json.dumps(snap, indent=2, sort_keys=True))
                elif not snap.get("enabled"):
                    print("resharding disabled")
                else:
                    lay = snap.get("layout")
                    print("gen %d%s  ticks %d  swaps %d  threshold %.2f  "
                          "sustain %.0fs  cooldown %.0fs" % (
                              snap["gen"],
                              " (%s)" % snap["mode"] if snap["mode"]
                              else "",
                              snap["ticks"], snap["swaps"],
                              snap["threshold"], snap["sustain"],
                              snap["min_interval"]))
                    skips = snap.get("skips") or {}
                    print("skips: %s" % (", ".join(
                        "%s=%d" % kv for kv in sorted(skips.items()))
                        or "none"))
                    if snap.get("latched_s") is not None:
                        print("imbalance above threshold for %.1fs"
                              % snap["latched_s"])
                    if lay is not None:
                        print("layout t=%d edges %s  post-swap "
                              "imbalance %s" % (
                                  lay["t"], lay["edges"],
                                  "%.3f" % snap["post_imbalance"]
                                  if snap.get("post_imbalance")
                                  is not None else "unknown"))
                    else:
                        print("layout: uniform (no swap yet)")
            elif op == "cache":
                # hot-key serving cache (ISSUE-11): same snapshot the
                # proxy serves on GET /cache
                import json as _json
                snap = node.get_cache()
                if rest and rest[0] == "json":
                    print(_json.dumps(snap, indent=2, sort_keys=True))
                elif not snap.get("enabled"):
                    print("hot-key cache disabled")
                else:
                    ratio = snap["hit_ratio"]
                    print("occupancy %d/%d  hit ratio %s  hits %d  "
                          "misses %d" % (
                              snap["occupancy"], snap["capacity"],
                              "%.3f" % ratio if ratio is not None
                              else "unknown",
                              snap["hits"], snap["misses"]))
                    print("admissions %d  evictions %d  invalidations "
                          "%d  replica k %d->%d on %d hot key(s)" % (
                              snap["admissions"], snap["evictions"],
                              snap["invalidations"],
                              snap["replica_k"]["base"],
                              snap["replica_k"]["widened"],
                              len(snap["hot_keys"])))
                    for ent in snap["entries"]:
                        print("  %s  %d value(s)  %d hit(s)%s  ttl %.1fs"
                              % (ent["key"], ent["values"], ent["hits"],
                                 "  store-backed" if ent["store_backed"]
                                 else "", ent["ttl_s"]))
                    if not snap["entries"]:
                        print("  (no hot keys cached yet)")
            elif op == "profile":
                # per-op latency waterfall (ISSUE-15): same snapshot
                # the proxy serves on GET /profile (?fmt=folded for
                # the 'folded' form)
                import json as _json
                if rest and rest[0] == "folded":
                    from .. import waterfall as _wf
                    print(_wf.get_profiler().folded(), end="")
                    continue
                snap = node.get_profile()
                if rest and rest[0] == "json":
                    print(_json.dumps(snap, indent=2, sort_keys=True))
                elif not snap.get("enabled"):
                    print("waterfall profiler disabled")
                else:
                    budgets = snap.get("budgets", {})
                    print("%-16s %8s %10s %10s %10s %10s" % (
                        "stage", "count", "p50 ms", "p95 ms", "p99 ms",
                        "budget ms"))
                    for stage, d in snap["stages"].items():
                        if not d.get("count") or d.get("alias_of"):
                            continue
                        print("%-16s %8d %10.3f %10.3f %10.3f %10.1f" % (
                            stage, d["count"], d["p50"] * 1e3,
                            d["p95"] * 1e3, d["p99"] * 1e3,
                            budgets.get(stage, 0.0) * 1e3))
                    ops = snap.get("ops", [])
                    print("%d per-op record(s) retained" % len(ops))
                    ob = snap.get("open_bounds")
                    if ob:
                        print("open bounds (%s, status %s):" % (
                            ob["platform"], ob["status"]))
                        for key_, b in sorted(ob["bounds"].items()):
                            print("  %-26s %s" % (
                                key_, "%.3f" % b["value"]
                                if b["value"] is not None
                                else "no measurement"))
            elif op == "pipeline":
                # pipeline utilization observatory (round 22,
                # ISSUE-18): same snapshot the proxy serves on
                # GET /pipeline
                import json as _json
                snap = node.get_pipeline()
                if rest and rest[0] == "json":
                    print(_json.dumps(snap, indent=2, sort_keys=True))
                elif not snap.get("enabled"):
                    print("pipeline observatory disabled")
                else:
                    occ = snap.get("occupancy", -1.0)
                    print("occupancy %s (window %.0fs)  depth %d  "
                          "inflight %d (peak %d)  overlap %s" % (
                              "%.1f%%" % (occ * 100) if occ >= 0
                              else "unknown",
                              snap.get("window_s", 0.0),
                              snap.get("pipeline_depth", 1),
                              snap.get("inflight", 0),
                              snap.get("inflight_peak", 0),
                              "%.2fx" % snap["overlap_ratio"]
                              if snap.get("overlap_ratio", -1) >= 0
                              else "unknown"))
                    print("%d wave(s), device busy %.3fs total" % (
                        snap.get("waves_total", 0),
                        snap.get("busy_seconds_total", 0.0)))
                    bubbles = snap.get("bubbles", {})
                    for cause, d in bubbles.items():
                        if d.get("count"):
                            print("  bubble %-18s %6d gap(s) %8.3fs" % (
                                cause, d["count"], d["seconds"]))
                    top = snap.get("top_bubble_cause")
                    print("top bubble cause: %s" % (top or "none"))
            elif op == "peers":
                # per-peer network observatory (round 23, ISSUE-19):
                # same snapshot the proxy serves on GET /peers
                import json as _json
                snap = node.get_peers()
                if rest and rest[0] == "json":
                    print(_json.dumps(snap, indent=2, sort_keys=True))
                elif not snap.get("enabled"):
                    print("peer ledger disabled")
                else:
                    print("%d peer(s) tracked (capacity %d, %d "
                          "evicted), adaptive RTO %s" % (
                              snap.get("tracked", 0),
                              snap.get("capacity", 0),
                              snap.get("evicted", 0),
                              "on" if snap.get("adaptive_rto")
                              else "off"))
                    print("%-28s %-8s %9s %9s %6s %6s %6s %5s" % (
                        "peer", "status", "srtt_ms", "rto_ms", "sent",
                        "done", "exp", "flap"))
                    for p in snap.get("peers", []):
                        print("%-28s %-8s %9s %9.1f %6d %6d %6d %5d"
                              % (p["peer"][:28], p["status"] or "?",
                                 "%.1f" % (p["srtt"] * 1e3)
                                 if p["srtt"] is not None else "-",
                                 p["rto"] * 1e3, p["sent"],
                                 p["completed"], p["expired"],
                                 p["flaps"]))
                    fs = snap.get("fail_signal")
                    print("worst-link fail ratio: %s" % (
                        "%.2f" % fs if fs is not None else "unknown"))
            elif op == "listeners":
                # device-resident listener table (round 24, ISSUE-20):
                # same snapshot the proxy serves on GET /listeners
                import json as _json
                snap = node.get_listeners()
                if rest and rest[0] == "json":
                    print(_json.dumps(snap, indent=2, sort_keys=True))
                elif not snap.get("enabled"):
                    print("listener table disabled (batching %s)" % (
                        snap.get("batching", "?"),))
                else:
                    print("%d/%d key(s) tracked (+%d overflow, %d "
                          "tombstone(s)), %d key(s) buffered" % (
                              snap.get("occupancy", 0),
                              snap.get("capacity", 0),
                              snap.get("overflow", 0),
                              snap.get("tombstones", 0),
                              snap.get("buffered", 0)))
                    print("flushes %d, matches %d, misses %d, "
                          "deliveries %d (%d value(s)), compactions %d"
                          % (snap.get("flushes", 0),
                             snap.get("matches", 0),
                             snap.get("misses", 0),
                             snap.get("deliveries", 0),
                             snap.get("values_delivered", 0),
                             snap.get("compactions", 0)))
                    lag = snap.get("lag_p95_s")
                    print("delivery lag p95: %s" % (
                        "%.1f ms" % (lag * 1e3)
                        if lag is not None and lag >= 0
                        else "unknown"))
                    for e in snap.get("entries", []):
                        print("  %s expires in %6.1fs" % (
                            e["key"], e["ttl_s"]))
            elif op == "bundle":
                # post-mortem black-box bundle (round 17): same
                # artifact the proxy serves on GET /debug/bundle
                import json as _json
                b = node.dump_bundle()
                if rest:
                    with open(rest[0], "w") as fh:
                        _json.dump(b, fh, indent=1, sort_keys=True)
                    print("bundle written to %s" % rest[0])
                h = b.get("history", {})
                print("bundle: %d history frame(s) (period %ss), %d "
                      "flight event(s) + %d span(s), verdict %s" % (
                          len(h.get("frames", [])),
                          h.get("period", "?"),
                          len(b["flight_recorder"]["events"]),
                          len(b["flight_recorder"]["spans"]),
                          b.get("health", {}).get("verdict", "unknown")))
                for a in b.get("auto_captures", []):
                    tr_ = a.get("transition") or {}
                    print("  auto-captured %s: %s -> %s (causes %s)" % (
                        time.strftime("%H:%M:%S",
                                      time.localtime(a.get("time", 0))),
                        tr_.get("from", "?"), tr_.get("to", "?"),
                        ", ".join(tr_.get("causes", [])) or "-"))
                if not b.get("auto_captures"):
                    print("  (no auto-captured bundles retained)")
            elif op == "dump":
                import json as _json
                n, name = 40, None
                for arg in rest[:2]:
                    if arg.isdigit():
                        n = int(arg)
                    else:
                        name = arg       # e.g. 'dump health'
                d = node.get_flight_recorder(limit=n, name=name)
                print(_json.dumps(d["events"], indent=2, sort_keys=True))
                print("flight recorder: %d/%d events shown%s, %d spans, "
                      "ring capacity %d" % (
                          len(d["events"]), n,
                          " (filter %r)" % name if name else "",
                          len(d["spans"]), d["capacity"]))
            elif op == "ll":
                d = node._dht
                for af in (socket.AF_INET,):
                    print(d.get_routing_tables_log(af))
                print(d.get_searches_log())
                print(d.get_storage_log())
            elif op == "lr":
                print(node._dht.get_routing_tables_log(socket.AF_INET))
            elif op == "ls":
                print(node._dht.get_searches_log())
            elif op == "la":
                print(node._dht.get_storage_log())
            elif op == "b":
                bs = parse_bootstrap(rest[0])
                node.bootstrap(*bs)
                print("bootstrapping %s:%d" % bs)
            elif op == "cc":
                node._post(lambda dht: dht.connectivity_changed(),
                           prio=True)
                print("connectivity change signalled")
            elif op == "g":
                key = to_hash(rest[0])
                t0 = time.monotonic()
                vals = node.get_sync(key, timeout=30.0)
                dt = time.monotonic() - t0
                for v in vals:
                    print("  %s" % _value_str(v))
                print("Get: %d value(s) in %.3fs" % (len(vals), dt))
            elif op == "q?":
                from ..core.value import Query
                key = to_hash(rest[0])
                q_str = " ".join(rest[1:])
                if ("where" not in q_str.lower()
                        and "select" not in q_str.lower()):
                    q_str = "where " + q_str    # 'q? <hash> id=42' shorthand
                q = Query(q_str)
                node.query(key, lambda fields: print("  fields: %s" % fields)
                           or True, lambda ok, ns: print("Query done: %s" % ok),
                           q)
            elif op == "l":
                key = to_hash(rest[0])
                tok = node.listen(key, lambda vals, expired: [
                    print("  %s %s" % ("EXPIRED" if expired else "LISTEN",
                                       _value_str(v))) for v in vals
                ] or True)
                t = tok.result(10.0)
                listen_tokens[t] = key
                print("listening, token %d" % t)
            elif op == "cl":
                t = int(rest[0])
                node.cancel_listen(listen_tokens.pop(t), t)
                print("cancelled %d" % t)
            elif op in ("p", "pp"):
                key = to_hash(rest[0])
                v = Value(" ".join(rest[1:]).encode())
                ok = node.put_sync(key, v, timeout=30.0,
                                   permanent=(op == "pp"))
                # the node assigns the random value id; 'cpp' needs it
                print("Put: %s (id %x)" % (ok, v.id))
            elif op == "cpp":
                node.cancel_put(to_hash(rest[0]),
                                int(rest[1], 16))
                print("cancelled")
            elif op == "s":
                key = to_hash(rest[0])
                done = []
                node.put_signed(key, Value(" ".join(rest[1:]).encode()),
                                lambda ok, ns: done.append(ok))
                _wait(done)
                print("PutSigned: %s" % (done and done[0]))
            elif op == "e":
                key = to_hash(rest[0])
                to = to_hash(rest[1])
                done = []
                node.put_encrypted(key, to,
                                   Value(" ".join(rest[2:]).encode()),
                                   lambda ok, ns: done.append(ok))
                _wait(done)
                print("PutEncrypted: %s" % (done and done[0]))
            elif op in ("il", "ii"):
                name = rest[0]
                if name not in indexes:
                    indexes[name] = Pht(name, {"k": 20}, node)
                pht = indexes[name]
                field = rest[1].encode()
                done = []
                if op == "il":
                    vid = int(rest[2]) if len(rest) > 2 else 1
                    pht.insert({"k": bytes(InfoHash.get(field))},
                               (node.get_node_id(), vid),
                               lambda ok: done.append(ok))
                    _wait(done)
                    print("Index insert: %s" % (done and done[0]))
                else:
                    pht.lookup({"k": bytes(InfoHash.get(field))},
                               cb=lambda vals, prefix: print(
                                   "  index values: %s" % (vals,)),
                               done_cb=lambda ok: done.append(ok))
                    _wait(done)
                    print("Lookup: %s" % (done and done[0]))
            elif op == "log":
                # toggle / route logging (↔ dhtnode.cpp:87-96)
                from ..log import DhtLogger
                if not hasattr(node, "_cli_logger"):
                    node._cli_logger = DhtLogger()
                lg = node._cli_logger
                arg = rest[0] if rest else "on"
                if arg == "off":
                    lg.disable()
                    print("logging off")
                elif arg == "file":
                    lg.set_sink_file(rest[1])
                    print("logging to %s" % rest[1])
                elif arg == "syslog":
                    lg.set_sink_syslog()
                    print("logging to syslog")
                elif len(arg) == 2 * InfoHash.HASH_LEN:
                    lg.set_filter(InfoHash(arg))
                    lg.set_sink_console()
                    print("logging filtered to %s" % arg)
                else:
                    lg.set_filter(None)
                    lg.set_sink_console()
                    print("logging on")
            elif op == "stt":
                from ..proxy import DhtProxyServer
                if proxy_server is not None:
                    proxy_server.stop()
                proxy_server = DhtProxyServer(node, int(rest[0]))
                print("proxy server on port %d" % proxy_server.port)
            elif op == "stp":
                if proxy_server:
                    proxy_server.stop()
                    proxy_server = None
                    print("proxy server stopped")
            elif op == "pst":
                node.enable_proxy(rest[0])
                print("backend switched to proxy %s" % rest[0])
            elif op == "psp":
                node.enable_proxy(None)
                print("backend switched to UDP")
            else:
                print("unknown op %r (h for help)" % op)
        except IndexError:
            print("missing argument (h for help)")
        except Exception as e:
            print("error: %s" % e)
    if proxy_server:
        proxy_server.stop()


def _wait(done, timeout=30.0):
    t0 = time.monotonic()
    while not done and time.monotonic() - t0 < timeout:
        time.sleep(0.02)


def main(argv=None) -> int:
    """(↔ main, dhtnode.cpp:480-545)"""
    p = make_arg_parser("OpenDHT-TPU node CLI")
    p.add_argument("--daemon", action="store_true",
                   help="run non-interactively (Ctrl-C to stop)")
    p.add_argument("--save-state", default="",
                   help="persist nodes+values to this file on exit and "
                        "restore them on start (checkpoint/resume)")
    args = p.parse_args(argv)
    node = setup_node(args)
    print_node_info(node)
    # SIGTERM (systemd/docker stop) must run the finally block so
    # --save-state persists for daemon deployments
    import signal as _signal

    def _on_term(signum, frame):
        raise KeyboardInterrupt

    try:
        _signal.signal(_signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        pass     # not the main thread / unsupported platform
    if args.save_state:
        import os as _os
        if _os.path.exists(args.save_state):
            from .common import load_state
            try:
                n_nodes, n_keys = load_state(node, args.save_state)
                print("restored %d nodes, %d keys from %s"
                      % (n_nodes, n_keys, args.save_state))
            except Exception as e:
                # a corrupt state file must not keep the node from
                # starting (the save path warns symmetrically)
                print("state restore failed: %s" % e)
    proxy_server = None
    if args.proxyserver:
        from ..proxy import DhtProxyServer
        proxy_server = DhtProxyServer(node, args.proxyserver)
        print("proxy server on port %d" % proxy_server.port)
    try:
        if args.daemon:
            while True:
                time.sleep(3600)
        else:
            cmd_loop(node, args)
    except KeyboardInterrupt:
        pass
    finally:
        if args.save_state:
            try:
                from .common import save_state
                save_state(node, args.save_state)
                print("state saved to %s" % args.save_state)
            except Exception as e:
                print("state save failed: %s" % e)
        if proxy_server:
            proxy_server.stop()
        node.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
