"""dhtscanner: census the network by walking the keyspace
(↔ reference tools/dhtscanner.cpp:40-135: search successive ids spread
over the ring, collecting every node seen in replies).

``--json`` (ISSUE-4 satellite) emits one machine-readable document —
the scanning node's topology snapshot (node id, per-bucket fill,
known-node count, storage size, recent flight-recorder events) plus
the discovered peer map — so the cluster harness can diff topology
over a soak run instead of scraping human output."""

from __future__ import annotations

import json
import socket
import sys
import time

from ..infohash import InfoHash

# .common imports the crypto layer at module scope; keep it a CALL-time
# dependency so the scan/snapshot helpers import (and the soak harness
# runs) without the `cryptography` wheel — same pattern as the lazy
# crypto re-exports in opendht_tpu/__init__.py


def scan(node, rounds: int = 32, timeout: float = 15.0,
         quiet: bool = False) -> dict:
    """Issue `rounds` gets at ids evenly spaced over the 160-bit ring;
    harvest the union of nodes from the routing table after each
    (dhtscanner.cpp:52-99 steps a prefix counter the same way)."""
    seen = {}
    for i in range(rounds):
        target = InfoHash.from_int((i << 152) | (1 << 151))
        done = []
        node.get(target, lambda vals: True,
                 lambda ok, nodes: done.append([
                     (n.id, n.addr) for n in nodes or []]))
        t0 = time.monotonic()
        while not done and time.monotonic() - t0 < timeout:
            time.sleep(0.02)
        for nid, addr in (done[0] if done else []):
            seen[nid] = addr
        if not quiet:
            print("scan %2d/%d: target %s…, %d nodes known"
                  % (i + 1, rounds, str(target)[:8], len(seen)))
    return seen


def topology_snapshot(node) -> dict:
    """Per-node topology/routing snapshot off ``get_metrics()`` + the
    flight-recorder ring: stable keys, JSON-able values, cheap enough
    to take every soak tick.  Every section degrades to empty rather
    than raising (a half-up node must still snapshot)."""
    snap: dict = {
        "node_id": str(node.get_node_id()),
        "port": node.get_bound_port(),
        "routing": {},
        "bucket_fill": [],
        "known_nodes": 0,
        "storage": {},
        "metrics_gauges": {},
        "maintenance": {},
        "ingest": {},
        "kernels": {},
        "health": {},
        "keyspace": {},
        "cache": {},
        "reshard": {},
        "waterfall": {},
        "pipeline": {},
        "peers": {},
        "listeners": {},
        "chaos": {},
        "events": [],
    }
    try:
        # round-19 latency waterfall: per-stage p50/p95/p99 + budgets +
        # the live OPEN-bound comparison, so a soak diff shows WHERE an
        # op's milliseconds went between snapshots, not just the
        # end-to-end total
        snap["waterfall"] = node.get_profile()
    except Exception:
        pass
    try:
        # round-22 pipeline observatory: windowed device occupancy,
        # per-cause bubble attribution and overlap ratio, so a soak
        # diff shows WHETHER the device stayed busy between snapshots
        # and whose fault the gaps were
        snap["pipeline"] = node.get_pipeline()
    except Exception:
        pass
    try:
        # round-23 per-peer observatory: srtt/RTO, outcome counts and
        # flap transitions per remote peer, so a soak diff shows WHICH
        # link degraded between snapshots (and the wire-map assembler
        # can rebuild the cluster's directed link graph offline)
        snap["peers"] = node.get_peers()
    except Exception:
        pass
    try:
        # round-24 listener table: occupancy/overflow, buffered keys
        # and delivery-lag p95, so a soak diff shows WHETHER the
        # wave-batched listen/push path kept up between snapshots
        # (next to the peers section's view of the links it pushed on)
        snap["listeners"] = node.get_listeners()
    except Exception:
        pass
    try:
        # round-16 hot-key serving cache: occupancy, hit ratio and the
        # widened hot set, so a soak diff shows WHICH keys the acting
        # layer served from cache (next to the keyspace section's
        # detection of them)
        snap["cache"] = node.get_cache()
    except Exception:
        pass
    try:
        # round-15 keyspace observatory: heavy hitters, occupied-bin
        # histogram and per-shard load attribution, so a soak diff
        # shows WHERE in the ring traffic moved between snapshots (the
        # full 256-bin histogram rides along — it is 256 ints)
        snap["keyspace"] = node.get_keyspace()
    except Exception:
        pass
    try:
        # round-21 load-aware resharding: layout generation, solved
        # edges and reason-labeled skip counters, so a soak diff shows
        # WHEN the boundaries moved (next to the keyspace section's
        # load attribution that triggered it)
        snap["reshard"] = node.get_reshard()
    except Exception:
        pass
    try:
        # round-14 health observatory: the node verdict + per-signal /
        # per-SLO attribution, so a soak diff shows WHEN a node
        # degraded and what drove it, not just that counters moved
        snap["health"] = node.get_health()
    except Exception:
        pass
    try:
        # round-12 ingest surface: the wave builder's queue depth /
        # occupancy p50-p95 / time-in-queue / shed state, so the soak
        # harness can diff how well live traffic coalesced (and whether
        # backpressure fired) between snapshots
        snap["ingest"] = node._dht.wave_builder.snapshot()
    except Exception:
        pass
    try:
        # kernel cost ledger (ISSUE-6): report whatever is already
        # computed — the snapshot must stay cheap enough for every soak
        # tick, so it never triggers the (seconds-long) lowering itself;
        # `dhtscanner --kernels` / the REPL `kernels` cmd arm it
        from .. import profiling
        if profiling.ledger_computed():
            snap["kernels"] = profiling.get_ledger().snapshot()
    except Exception:
        pass
    try:
        metrics = node.get_metrics()
        snap["metrics_gauges"] = {
            k: v for k, v in metrics.get("gauges", {}).items()
            if k.startswith(("dht_routing_", "dht_scheduler_"))}
        # round-10 maintenance surface: sweep/refresh/republish counters
        # + calendar-bin gauge, so the soak harness can diff how much
        # maintenance each node actually performed between snapshots
        snap["maintenance"] = {
            k: v for k, v in metrics.get("counters", {}).items()
            if k.startswith("dht_maintenance_")}
        snap["maintenance"].update(
            (k, v) for k, v in metrics.get("gauges", {}).items()
            if k.startswith("dht_maintenance_"))
        # round-18 chaos plane (ISSUE-15 satellite): the fault
        # injector's per-rule drop/dup/reorder/delay accounting
        # (dht_chaos_injected_total{action=,rule=}) — armed storms were
        # counted on the registry but surfaced nowhere; a soak diff now
        # shows which rules actually fired between snapshots
        snap["chaos"] = {
            k: v for k, v in metrics.get("counters", {}).items()
            if k.startswith("dht_chaos_")}
    except Exception:
        pass
    for af, fam in ((socket.AF_INET, "ipv4"), (socket.AF_INET6, "ipv6")):
        try:
            st = node.get_node_stats(af)
            snap["routing"][fam] = st.to_dict()
            snap["known_nodes"] += st.get_known_nodes()
        except Exception:
            continue
    try:
        table = node._dht.tables[socket.AF_INET]
        snap["bucket_fill"] = [int(c) for c in table.bucket_occupancy()]
    except Exception:
        pass
    try:
        dht = node._dht
        snap["storage"] = {
            "keys": len(dht.store),
            "values": int(dht.total_values),
            "bytes": int(dht.total_store_size),
        }
    except Exception:
        pass
    try:
        snap["events"] = node.get_flight_recorder(limit=50)["events"]
    except Exception:
        pass
    return snap


def main(argv=None) -> int:
    from .common import make_arg_parser, print_node_info, setup_node
    p = make_arg_parser("OpenDHT-TPU network scanner")
    p.add_argument("--rounds", type=int, default=32,
                   help="number of keyspace probes")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON document (topology snapshot + "
                        "discovered peers) instead of human output")
    p.add_argument("--kernels", action="store_true",
                   help="compute the kernel cost ledger (seconds of "
                        "one-time lowering) so the snapshot's 'kernels' "
                        "section carries per-kernel flops/bytes/HBM "
                        "footprint")
    p.add_argument("--bundle", default="", metavar="DIR",
                   help="collect the scanning node's post-mortem "
                        "black-box bundle (round 17: last-N history "
                        "frames + flight ring + kernel ledger + "
                        "keyspace/cache snapshots — the GET "
                        "/debug/bundle artifact) into "
                        "DIR/bundle-<nodeid>.json after the scan")
    args = p.parse_args(argv)
    if args.kernels:
        from .. import profiling
        profiling.get_ledger().compute()
        profiling.maybe_export()
    node = setup_node(args)
    if not args.json:
        print_node_info(node)
    try:
        # wait for connectivity before scanning (dhtscanner.cpp:109-117)
        from ..runtime.config import NodeStatus
        t0 = time.monotonic()
        while (node.get_status() is not NodeStatus.CONNECTED
               and time.monotonic() - t0 < 30.0):
            time.sleep(0.1)
        seen = scan(node, args.rounds, quiet=args.json)
        stats = node.get_node_stats(socket.AF_INET)
        bundle_path = None
        if args.bundle:
            # black-box collector (round 17): the scan drove real
            # traffic, so the bundle's history frames carry it — one
            # artifact per node for the cluster harness to merge
            # through testing/timeline_assembler.py
            import os
            os.makedirs(args.bundle, exist_ok=True)
            bundle_path = os.path.join(
                args.bundle,
                "bundle-%s.json" % node.get_node_id().hex())
            with open(bundle_path, "w") as fh:
                json.dump(node.dump_bundle(reason="dhtscanner"), fh)
            if not args.json:
                print("bundle written to %s" % bundle_path)
        if args.json:
            doc = {
                "snapshot": topology_snapshot(node),
                "discovered": sorted(
                    ([str(nid), [str(addr.ip), addr.port]]
                     for nid, addr in seen.items()),
                    key=lambda kv: kv[0]),
                "network_size_estimation":
                    stats.get_network_size_estimation(),
                "bundle_path": bundle_path,
            }
            json.dump(doc, sys.stdout)
            print()
        else:
            print("\n%d nodes discovered:" % len(seen))
            for nid, addr in sorted(seen.items(), key=lambda kv: str(kv[0])):
                print("  %s  %s" % (nid, addr))
            print("network size estimation: %d"
                  % stats.get_network_size_estimation())
    finally:
        node.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
