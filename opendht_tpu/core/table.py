"""The node table: a growable slab of known peers with k-bucket admission
and device-snapshot queries.

This replaces three reference structures with one:

- ``RoutingTable``/``Bucket`` (include/opendht/routing_table.h:26-97,
  src/routing_table.cpp) — k=8 buckets split around the own id.  Here
  buckets are *implicit*: bucket(peer) = commonBits(self, peer) (see
  ops/radix.py); admission keeps ≤ k non-expired peers per bucket, which
  is the steady state the reference's split rule converges to.
- ``NodeCache`` (src/node_cache.cpp) — the interning map of every peer
  ever heard of; here the slab itself, with a host dict for O(1) id→row.
- ``Node`` liveness state (include/opendht/node.h:73-158) — the
  good/dubious/expired timers become per-row columns.

Host/device split (the architectural core of the TPU build): per-packet
mutations are O(1) host-side numpy/dict updates; *all* closest-node
queries go through an immutable device ``Snapshot`` (sorted id matrix +
permutation) built lazily and reused until the table changes.  That
turns the reference's per-search scalar scans
(``findClosestNodes`` src/routing_table.cpp:109-150,
``getCachedNodes`` src/node_cache.cpp:41-74) into one batched
sorted-window top-k (ops/sorted_table.py) over thousands of concurrent
targets.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .. import telemetry
from ..infohash import InfoHash
from ..ops import ids as IK
from ..ops import radix
from ..ops.sorted_table import (_resolve_merge_pack, sort_table, lookup_topk,
                                expand_table, churn_lookup_topk)

# liveness windows (reference include/opendht/node.h:148-158)
NODE_GOOD_TIME = 120 * 60.0       # replied within 2 h → good
NODE_EXPIRE_TIME = 10 * 60.0      # silent for 10 min → expirable
MAX_RESPONSE_TIME = 1.0           # per-attempt RPC timeout
MAX_AUTH_ERRORS = 3               # 3 strikes → expired (node.h:73-77)

TARGET_NODES = 8                  # k (routing_table.h:26)
SEARCH_NODES = 14                 # search candidate set (dht.h:308)

DELTA_CAP = 4096                  # churn side-slab capacity (inserts
                                  # absorbed without re-sorting)
TOMB_MIN = 1024                   # compact when tombstones exceed
TOMB_FRAC = 16                    # max(TOMB_MIN, n_base // TOMB_FRAC)

# compactions are a first-class perf signal (every full re-sort+re-expand
# stalls behind a device sort): counted per-process alongside each
# NodeTable's own ``compactions`` attribute
_M_COMPACTIONS = telemetry.get_registry().counter(
    "dht_table_compactions_total")

# Below these sizes closest-node queries run as an exact numpy scan on
# the host slab instead of a device kernel: a live protocol node's
# table is tens-to-hundreds of rows, where one XLA compile (~10 s on a
# CPU backend) or even one device round-trip dwarfs the O(Q·N) scan.
# The device path (snapshot/churn kernels) is for simulation-scale
# tables and query waves, where it is the headline win.
HOST_SCAN_MAX_ROWS = 4096
HOST_SCAN_MAX_QUERIES = 64


@dataclasses.dataclass
class NodeView:
    """Host-side view of one table row (≈ reference Node, node.h)."""

    row: int
    id: InfoHash
    addr: Any
    time_reply: float
    time_seen: float
    expired: bool

    def is_good(self, now: float) -> bool:
        return (not self.expired) and self.time_reply > 0 and \
            now - self.time_reply < NODE_GOOD_TIME


class PendingLookup:
    """Handle for an in-flight (dispatched, not yet consumed) batched
    closest-node resolve — the round-20 async seam.

    JAX dispatch is asynchronous: the device kernel is launched when
    ``lookup_launch``/``find_closest_launch`` returns, but the blocking
    ``np.asarray`` transfer (and the host-side row mapping behind it)
    is deferred into :meth:`consume`.  ``ready()`` is a non-blocking
    probe (``jax.Array.is_ready``) so a caller — the wave-builder
    pipeline — can fill and launch wave N+1 while wave N still runs on
    device, and only pay the wait where the results are actually used.

    The finalize closure must capture every piece of mutable host
    state it maps through (churn-view ``delta_rows``/``_d_perm``, the
    launch-time ``now``) AT LAUNCH TIME: the table may mutate between
    launch and consume, and depth-1 equivalence requires the mapping
    the synchronous path would have used.  Row→id/addr materialization
    above this seam (``ids_of_rows``/``addr_of``) still reads the live
    slab at consume; the one-pump window is sub-millisecond and an
    eviction+row-reuse inside it resolves against the row's current
    occupant — same class of benign race the synchronous path has
    between resolve and RPC send.

    ``consume()`` is idempotent (caches its result and drops the device
    refs) so ``lookup(...) = lookup_launch(...).consume()`` is the ONE
    codepath for both the synchronous and pipelined forms."""

    __slots__ = ("_finalize", "_probe", "_done", "_result")

    def __init__(self, finalize, probe=None):
        self._finalize = finalize         # () -> result tuple
        self._probe = probe               # device array or None (=ready)
        self._done = False
        self._result = None

    @classmethod
    def resolved(cls, *result):
        """An already-materialized result (host-scan fast path)."""
        pl = cls(None)
        pl._done = True
        pl._result = result if len(result) != 1 else result[0]
        return pl

    def ready(self) -> bool:
        """Non-blocking: True when consume() will not wait on device."""
        if self._done or self._probe is None:
            return True
        try:
            return bool(self._probe.is_ready())
        except AttributeError:            # numpy / stub result
            return True

    def consume(self):
        """Block until the device work finishes, materialize, cache."""
        if not self._done:
            self._result = self._finalize()
            self._done = True
            self._finalize = None
            self._probe = None
        return self._result


class Snapshot:
    """Immutable device view: lexicographically sorted ids + row map."""

    def __init__(self, sorted_ids, perm, n_valid, version: int, mask_key):
        self.sorted_ids = sorted_ids      # uint32 [cap, 5] device
        self.perm = perm                  # int32 [cap] sorted→row (-1 pad)
        self.n_valid = n_valid            # int32 scalar
        self.version = version
        self.mask_key = mask_key
        self._expanded = None             # lazy expand_table
        self._tp_state = None             # lazy (mesh, placed dict)

    def lookup(self, queries, *, k: int = TARGET_NODES, window: int = 128,
               mesh=None, layout=None):
        """Batched exact k-closest.  queries: uint32 [Q,5] (device or np).
        Returns (rows [Q,k] int32 numpy, dist [Q,k,5] numpy) with -1 padding.

        Uses the expanded row-gather fast path (built lazily per
        snapshot — the table is immutable until the next version) with
        the default fast3 select, which carries all five distance limbs.
        ``window`` is accepted for API symmetry with the non-expanded
        path but IGNORED here: the candidate window is fixed at
        EXPAND_LEN=192 rows, and uncertified queries fall back to the
        exact full scan on device inside lookup_topk.  No prefix LUT:
        routing-table ids cluster around self_id by design, so LUT
        buckets degenerate — the plain log2(cap)-step positioning
        search is both exact and cheap at routing-table sizes.

        ``mesh`` (round 13, ``config.resolve_mesh_t``): a (q=1, t)
        device mesh row-shards the resolve — per-shard windowed top-k
        over each shard's contiguous slice of the sorted slab, ONE
        cross-shard merge collective (parallel/sharded.py
        ``sharded_window_lookup``) — so the resolve table scales past
        one device's HBM.  Exact either way; results identical (the
        window kernel's certificate decertifies into the shard-local
        full scan).

        ``layout`` (ISSUE-17, load-aware resharding): an installed
        :class:`~opendht_tpu.reshard.ReshardLayout` moves the shard
        boundaries to traffic-weighted row splits of THIS snapshot —
        same merge kernel, same results, different ownership."""
        return self.lookup_launch(queries, k=k, window=window,
                                  mesh=mesh, layout=layout).consume()

    def lookup_launch(self, queries, *, k: int = TARGET_NODES,
                      window: int = 128, mesh=None,
                      layout=None) -> PendingLookup:
        """Async form of :meth:`lookup` (round-20 wave pipeline): the
        device kernel is dispatched before this returns; the blocking
        transfer + perm row-mapping are deferred into the handle's
        ``consume()``.  The per-wave query buffer is donated to the
        kernel when it is this call's own upload (non-CPU backends
        only — see ops/sorted_table._donating_lookup_topk)."""
        q = jnp.asarray(queries, jnp.uint32)
        if mesh is not None and mesh.shape.get("t", 1) > 1:
            return self._lookup_sharded_launch(mesh, q, k, window, layout)
        if self._expanded is None:
            self._expanded = expand_table(self.sorted_ids)
        dist, idx, _ = lookup_topk(self.sorted_ids, self.n_valid, q, k=k,
                                   expanded=self._expanded,
                                   donate_queries=q is not queries)
        perm = self.perm

        def finalize(idx=idx, dist=dist, perm=perm):
            idx = np.asarray(idx)         # blocks on the device call
            rows = np.where(idx >= 0,
                            np.asarray(perm)[np.clip(idx, 0, None)], -1)
            return rows.astype(np.int32), np.asarray(dist)

        return PendingLookup(finalize, probe=idx)

    def reshard_boundary_rows(self, layout, n_t: int):
        """Traffic-weighted interior row boundaries of THIS snapshot
        for an installed reshard layout — re-derived per snapshot (raw
        row offsets go stale across rebuilds; the layout carries bin
        loads, not rows), cached by ``(layout.gen, t)``.

        Returns ``n_t - 1`` nondecreasing row indices into the valid
        prefix of the sorted order (parallel/partition.py
        ``solve_shard_boundaries``): the snapshot's per-bin row counts
        come from one searchsorted over the sorted top limb."""
        key = (int(layout.gen), int(n_t))
        cached = getattr(self, "_reshard_rows", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        from ..parallel.partition import solve_shard_boundaries
        n = int(self.n_valid)
        top = np.asarray(self.sorted_ids[:, 0]).astype(np.int64)
        edges_v = np.arange(1, 256, dtype=np.int64) << 24
        counts = np.searchsorted(top[:n], edges_v, side="left")
        bin_rows = np.diff(np.concatenate([[0], counts, [n]]))
        rows = solve_shard_boundaries(
            bin_rows, layout.bin_loads, n_t,
            load_weight=layout.load_weight)
        self._reshard_rows = (key, rows)
        return rows

    def _shard_state(self, mesh, layout=None):
        """Row-shard this snapshot's sorted slab over the mesh ``t``
        axis ONCE (declarative placement — parallel/partition.py) and
        cache the placed operands; subsequent waves reuse them with
        zero copies (the shard fns are placement-idempotent).

        With a reshard ``layout`` (ISSUE-17) the split is the
        traffic-weighted one: shard ``i`` owns rows
        ``[b_i, b_{i+1})`` of the sorted order, physically realized as
        equal-capacity slabs (rearranged rows + per-shard widths) so
        ``P('t', None)`` placement still sees equal chunks.  The cache
        key includes ``layout.gen`` — a hot swap is one attribute
        write on the DHT loop; the NEXT wave rebuilds here (row
        movement + placement, never a re-sort) while any wave already
        in flight keeps the operands and perm map its launch captured.

        Returns ``(placed, perm_host)``: ``perm_host`` is None for the
        uniform split (global sorted positions map through
        ``self.perm``) or the rearranged position→slab-row map for the
        weighted one."""
        st = self._tp_state
        key = (None if layout is None
               else (int(layout.gen), int(mesh.shape["t"])))
        if st is not None and st[0] is mesh and st[1] == key:
            return st[2], st[3]
        from ..parallel import partition
        from ..parallel.sharded import pad_to_multiple
        n_t = mesh.shape["t"]
        n = int(self.n_valid)
        if layout is not None:
            bnd = self.reshard_boundary_rows(layout, n_t)
            bounds = np.maximum.accumulate(
                np.concatenate([[0], np.clip(bnd, 0, n), [n]]))
            widths = np.diff(bounds)
            shard_cap = int(-(-max(int(widths.max()), 1)
                              // partition.RESHARD_ALIGN)
                            * partition.RESHARD_ALIGN)
            ids_np = np.asarray(self.sorted_ids, np.uint32)
            perm_np = np.asarray(self.perm)
            ids_re = np.zeros((n_t * shard_cap, ids_np.shape[1]), np.uint32)
            perm_host = np.full(n_t * shard_cap, -1, np.int32)
            for i in range(n_t):
                w = int(widths[i])
                lo, hi = int(bounds[i]), int(bounds[i + 1])
                ids_re[i * shard_cap:i * shard_cap + w] = ids_np[lo:hi]
                perm_host[i * shard_cap:i * shard_cap + w] = perm_np[lo:hi]
            nv = widths.astype(np.int32)
            perm_local = np.tile(np.arange(shard_cap, dtype=np.int32), n_t)
            placed = partition.shard_put(
                mesh, {"sorted_ids": ids_re, "perm": perm_local,
                       "n_valid": nv},
                partition.TABLE_AXIS_RULES)
            self._tp_state = (mesh, key, placed, perm_host)
            return placed, perm_host
        cap = self.sorted_ids.shape[0]
        ids = self.sorted_ids
        if cap % n_t:
            # append-pad on host; pad rows land past the valid prefix
            # (the last shard) and every shard excludes rows beyond its
            # local n_valid, so their content never participates
            ids, _ = pad_to_multiple(np.asarray(ids), n_t)
        shard_n = ids.shape[0] // n_t
        nv = np.clip(n - np.arange(n_t) * shard_n, 0,
                     shard_n).astype(np.int32)
        # per-shard LOCAL sorted positions: the sharded kernel offsets
        # them by the shard base, yielding global sorted positions that
        # this snapshot's perm then maps to slab rows host-side
        perm_local = np.tile(np.arange(shard_n, dtype=np.int32), n_t)
        placed = partition.shard_put(
            mesh, {"sorted_ids": ids, "perm": perm_local, "n_valid": nv},
            partition.TABLE_AXIS_RULES)
        self._tp_state = (mesh, key, placed, None)
        return placed, None

    def _lookup_sharded_launch(self, mesh, q, k: int, window: int,
                               layout=None) -> PendingLookup:
        from ..parallel.sharded import sharded_window_lookup
        placed, perm_host = self._shard_state(mesh, layout)
        dist, gpos = sharded_window_lookup(
            mesh, q, placed["sorted_ids"], placed["perm"],
            placed["n_valid"], k=k, window=window)
        # captured AT LAUNCH: a reshard swap between launch and consume
        # must not remap this wave's positions through the new layout
        perm = self.perm if perm_host is None else perm_host

        def finalize(gpos=gpos, dist=dist, perm=perm):
            gpos = np.asarray(gpos)       # blocks on the collective
            rows = np.where(gpos >= 0,
                            np.asarray(perm)[np.clip(gpos, 0, None)], -1)
            return rows.astype(np.int32), np.asarray(dist)

        return PendingLookup(finalize, probe=gpos)


class ChurnView:
    """Append+tombstone view over an immutable base :class:`Snapshot`
    (SURVEY §7 "incremental updates"; reference mutation path
    src/routing_table.cpp:204-262).

    Mutations since the base was built are absorbed host-side in O(1):
    evictions set one bit in a packed tombstone mask over *sorted
    positions* (dead rows stay in the device array as mere sort keys);
    inserts land in a small delta slab.  ``lookup`` runs
    ops/sorted_table.churn_lookup_topk — tombstone-masked window top-k
    over the base, window top-k over the delta (kept as its own mini
    sorted+expanded table, re-sorted lazily per mutation batch), one
    lane-packed merge (on TPU, 128//k queries share each 128-lane
    physical row, ops/sorted_table.packed_churn_merge — the round-7
    amortizer for the [Q, k] padding tax) — in a single device call,
    bit-identical to a full re-sort of the mutated id set.  Device
    state is refreshed lazily:
    tombstone words re-upload whole (1.25 MB per 10M rows — noise), the
    delta re-sorts on device (one small sort+expand per dirty batch).

    Correctness never depends on churn volume (a heavily-tombstoned
    window decertifies into the kernel's exact fallback), so compaction
    — dropping this view and rebuilding the base — is purely a
    performance policy, owned by :class:`NodeTable`.
    """

    def __init__(self, base: Snapshot, cap_rows: int,
                 delta_cap: int = DELTA_CAP):
        self.base = base
        n = base.sorted_ids.shape[0]
        perm = np.asarray(base.perm)
        self.n_base = int((perm >= 0).sum())
        self._perm = perm
        # slab row -> sorted position AT BASE-BUILD TIME.  Never re-read
        # after the row is freed+reused: inserts always go to the delta,
        # and note_evict checks delta membership first, so a stale
        # mapping is only ever used to tombstone the id that actually
        # occupied the position.
        self.inv_perm = np.full(cap_rows, -1, dtype=np.int64)
        pos = np.nonzero(perm >= 0)[0]
        self.inv_perm[perm[pos]] = pos
        self.tomb_np = np.zeros((n + 31) // 32, dtype=np.uint32)
        self.tomb_count = 0
        self.delta_ids_np = np.zeros((delta_cap, IK.N_LIMBS), dtype=np.uint32)
        self.delta_rows = np.full(delta_cap, -1, dtype=np.int64)
        self._delta_pos: dict[int, int] = {}
        self.n_delta = 0
        self._dev_tomb = None
        self._dev_delta = None            # (d_sorted, d_expanded, d_n_valid)
        self._d_perm = None               # delta sorted pos -> slot
        self._dirty_tomb = True
        self._dirty_delta = True

    @property
    def pending(self) -> int:
        return self.tomb_count + self.n_delta

    def grow_delta(self) -> None:
        """Double the delta slab in place (churn kernels recompile once
        per slab size — shapes recur, so a steady state is reached).
        Lets an overflowing delta keep absorbing inserts while a
        background compaction builds the next base (NodeTable
        ``_start_compaction``) instead of stalling a lookup behind a
        synchronous full rebuild."""
        dcap = self.delta_ids_np.shape[0]
        self.delta_ids_np = np.concatenate(
            [self.delta_ids_np, np.zeros_like(self.delta_ids_np)])
        self.delta_rows = np.concatenate(
            [self.delta_rows, np.full(dcap, -1, dtype=np.int64)])
        self._dirty_delta = True

    def note_insert(self, row: int, limbs) -> bool:
        """Absorb a newly-live slab row.  False = delta slab full (the
        caller must compact).  The row must NOT be live in the base:
        NodeTable only routes here rows that are new, revived after an
        expiry (whose base position the expiry tombstoned), or absent
        from the base mask at build time — so live ids stay unique
        across base and delta and merge order stays exact."""
        if row in self._delta_pos:
            return True
        if self.n_delta >= self.delta_ids_np.shape[0]:
            return False
        s = self.n_delta
        self.delta_ids_np[s] = limbs
        self.delta_rows[s] = row
        self._delta_pos[row] = s
        self.n_delta = s + 1
        self._dirty_delta = True
        return True

    def note_evict(self, row: int) -> None:
        """Absorb a row leaving the live set (evicted or expired).
        Delta membership is checked before the base mapping so a reused
        slab row never tombstones another id's position."""
        s = self._delta_pos.pop(row, None)
        if s is not None:
            last = self.n_delta - 1
            if s != last:
                self.delta_ids_np[s] = self.delta_ids_np[last]
                lrow = int(self.delta_rows[last])
                self.delta_rows[s] = lrow
                self._delta_pos[lrow] = s
            self.delta_rows[last] = -1
            self.n_delta = last
            self._dirty_delta = True
            return
        if 0 <= row < len(self.inv_perm):
            p = int(self.inv_perm[row])
            if p >= 0 and not (int(self.tomb_np[p >> 5]) >> (p & 31)) & 1:
                self.tomb_np[p >> 5] |= np.uint32(1) << (p & 31)
                self.tomb_count += 1
                self._dirty_tomb = True

    def lookup(self, queries, *, k: int = TARGET_NODES, window: int = 128):
        """Batched exact k-closest over (live base ∪ delta) — same
        contract as :meth:`Snapshot.lookup` (``window`` ignored).

        Host-side telemetry (ISSUE-3; the kernel itself is untouched):
        ``dht_churn_lookup_seconds`` spans the whole device call — the
        OPEN churny/static ≥0.6× bound (PARITY.md) is this histogram's
        p50 at an 8192 wave vs ``dht_search_wave_seconds`` on a static
        table; ``dht_churn_lookups_total{pack=}`` records which merge
        pack path the backend resolves ("auto" → 128//k on TPU, 1
        elsewhere); tombstone/delta gauges expose the view's churn
        debt."""
        return self.lookup_launch(queries, k=k, window=window).consume()

    def lookup_launch(self, queries, *, k: int = TARGET_NODES,
                      window: int = 128) -> PendingLookup:
        """Async form of :meth:`lookup` (round-20 wave pipeline).
        Telemetry and the lazy tombstone/delta device refresh happen at
        launch; the ``dht_churn_lookup_seconds`` histogram observes
        dispatch + blocking-wait at consume (same device interval the
        synchronous span covered).  The finalize closure captures
        ``delta_rows``/``_d_perm``/``_perm`` AT LAUNCH: ``note_evict``
        swap-removes delta slots in place and a delta re-sort replaces
        ``_d_perm`` wholesale, so mapping through the live view at
        consume could diverge from what this launch's kernel saw."""
        reg = telemetry.get_registry()
        reg.counter("dht_churn_lookups_total",
                    pack=_resolve_merge_pack("auto", k)).inc()
        reg.gauge("dht_churn_tombstones").set(self.tomb_count)
        reg.gauge("dht_churn_delta_rows").set(self.n_delta)
        q = jnp.asarray(queries, jnp.uint32)
        base = self.base
        if base._expanded is None:
            base._expanded = expand_table(base.sorted_ids)
        if self._dirty_tomb or self._dev_tomb is None:
            self._dev_tomb = jnp.asarray(self.tomb_np)
            self._dirty_tomb = False
        if self._dirty_delta or self._dev_delta is None:
            dcap = self.delta_ids_np.shape[0]
            dvalid = np.zeros(dcap, bool)
            dvalid[:self.n_delta] = True      # slots are prefix-dense
            ds, dp, dnv = sort_table(jnp.asarray(self.delta_ids_np),
                                     jnp.asarray(dvalid))
            self._dev_delta = (ds, expand_table(ds, stride=32), dnv)
            self._d_perm = np.asarray(dp)
            self._dirty_delta = False
        ds, de, dnv = self._dev_delta
        t0 = time.perf_counter()
        dist, enc, _ = churn_lookup_topk(
            base.sorted_ids, base._expanded, base.n_valid,
            self._dev_tomb, ds, de, dnv, q, k=k)
        dispatch_s = time.perf_counter() - t0
        n = base.sorted_ids.shape[0]
        d_perm = self._d_perm
        base_perm = self._perm
        delta_rows = self.delta_rows.copy()
        hist = reg.histogram("dht_churn_lookup_seconds")

        def finalize(dist=dist, enc=enc):
            t1 = time.perf_counter()
            enc = np.asarray(enc)           # blocks on the device call
            hist.observe(dispatch_s + (time.perf_counter() - t1))
            # enc in [n, n+D) is a *delta sorted position* → slot → slab row
            dslot = d_perm[np.clip(enc - n, 0, len(d_perm) - 1)]
            rows = np.where(
                enc < 0, -1,
                np.where(enc < n, base_perm[np.clip(enc, 0, n - 1)],
                         delta_rows[np.clip(dslot, 0, None)]))
            return rows.astype(np.int32), np.asarray(dist)

        return PendingLookup(finalize, probe=enc)


class NodeTable:
    """Growable peer slab with k-bucket admission (one per address family,
    like the reference's buckets4/buckets6, dht.h:370-381)."""

    def __init__(self, self_id: InfoHash, *, k: int = TARGET_NODES,
                 capacity: int = 1024, delta_cap: int = DELTA_CAP):
        self.self_id = self_id
        self.self_limbs = IK.ids_from_bytes(bytes(self_id)).reshape(-1)
        self.k = k
        self._cap = capacity
        self._delta_cap = delta_cap
        self._churn: Optional[ChurnView] = None
        self.compactions = 0              # full re-sort+re-expand count
        self._ids = np.zeros((capacity, IK.N_LIMBS), dtype=np.uint32)
        self._valid = np.zeros(capacity, dtype=bool)
        self._expired = np.zeros(capacity, dtype=bool)
        self._time_reply = np.zeros(capacity, dtype=np.float64)
        self._time_seen = np.zeros(capacity, dtype=np.float64)
        self._auth_err = np.zeros(capacity, dtype=np.int8)
        self._bucket = np.zeros(capacity, dtype=np.int16)
        self._addrs: list = [None] * capacity
        self._row_of: dict[bytes, int] = {}
        self._free: list[int] = list(range(capacity - 1, -1, -1))
        self._bucket_count = np.zeros(radix.ID_BITS, dtype=np.int32)
        # one cached replacement candidate per bucket (↔ Bucket::cached,
        # routing_table.h:31-45)
        self._cached: dict[int, tuple[bytes, Any]] = {}
        self._version = 0
        self._maint_key = None            # reusable refresh-target PRNG
                                          # key (lazy; split per use)
        self._snap: Optional[Snapshot] = None
        #: whether the most recent find_closest ran the t-sharded
        #: resolve (round 13) — host scans and churn views reset it
        self.last_resolve_sharded = False
        # in-flight background compaction: dispatched device arrays +
        # the mutation log to replay at swap (see _start_compaction)
        self._pending_base: Optional[dict] = None

    # ------------------------------------------------------------------ size
    def __len__(self) -> int:
        return len(self._row_of)

    @property
    def capacity(self) -> int:
        return self._cap

    def _grow(self) -> None:
        old = self._cap
        new = old * 2
        for name in ("_ids", "_valid", "_expired", "_time_reply", "_time_seen",
                     "_auth_err", "_bucket"):
            arr = getattr(self, name)
            grown = np.zeros((new,) + arr.shape[1:], dtype=arr.dtype)
            grown[:old] = arr
            setattr(self, name, grown)
        self._addrs.extend([None] * old)
        self._free.extend(range(new - 1, old - 1, -1))
        self._cap = new

    # ------------------------------------------------------------ liveness
    def good_mask(self, now: float) -> np.ndarray:
        return (
            self._valid
            & ~self._expired
            & (self._time_reply > 0)
            & (now - self._time_reply < NODE_GOOD_TIME)
        )

    def reachable_mask(self, now: float) -> np.ndarray:
        """Valid, non-expired nodes (good or dubious) — what lookups may
        contact (the reference inserts dubious nodes into searches too)."""
        return self._valid & ~self._expired

    def is_good(self, row: int, now: float) -> bool:
        return bool(self.good_mask(now)[row])

    # ------------------------------------------------------------- mutation
    def _touch(self, count_compaction: bool = True) -> None:
        """Structural change the churn view cannot absorb: drop both the
        base snapshot and the churn state (next view rebuilds).  A view
        carrying pending churn counts as a compaction — the rebuild it
        forces folds that churn into the next base.  ``count_compaction
        =False`` suppresses that increment for callers that already
        counted the same event (the replay-overflow path of
        :meth:`_maybe_swap`, which books its compaction before
        replaying — ADVICE r5 finding 2's double count)."""
        if count_compaction and self._churn is not None \
                and self._churn.pending:
            self.compactions += 1
            _M_COMPACTIONS.inc()
        self._version += 1
        self._snap = None
        self._churn = None
        self._pending_base = None        # dispatched from a stale state

    # -------------------------------------------- non-blocking compaction
    def _start_compaction(self) -> None:
        """Dispatch the next base build (full re-sort of the CURRENT
        host state) WITHOUT blocking: the device computes while the old
        snapshot + churn view keep serving every lookup exactly, and
        :meth:`_maybe_swap` installs the result once it is ready.
        Mutations that land between dispatch and swap are logged and
        replayed into the fresh view's churn state (host-side O(1)
        each), so no lookup ever waits behind the rebuild — the
        round-4 verdict's "overflow stalls a lookup" fix."""
        if self._pending_base is not None or self._snap is None:
            return
        m = self.reachable_mask(time.monotonic())
        sorted_ids, perm, n_valid = sort_table(
            jnp.asarray(self._ids), jnp.asarray(m))
        self._pending_base = {
            "sorted": sorted_ids, "perm": perm, "n_valid": n_valid,
            "mutlog": [],
        }

    def _maybe_swap(self, force: bool = False) -> bool:
        """Install a finished background compaction; with ``force`` wait
        for it.  Replays the post-dispatch mutation log into the new
        churn view so the swap is exact."""
        pb = self._pending_base
        if pb is None:
            return False
        nv = pb["n_valid"]
        if not force:
            ready = getattr(nv, "is_ready", None)
            if ready is not None and not ready():
                return False
        snap = Snapshot(pb["sorted"], pb["perm"], nv, self._version,
                        ("reachable", 0))
        self._snap = snap
        self._churn = ChurnView(snap, self._cap, self._delta_cap)
        self._pending_base = None
        self.compactions += 1
        _M_COMPACTIONS.inc()
        # flight recorder (ISSUE-4): churn swaps / compactions are
        # postmortem-grade events — when a lookup traces slow, the ring
        # shows whether a base swap landed mid-wave
        from .. import tracing
        _tr = tracing.get_tracer()
        if _tr.enabled:
            _tr.event("table_churn_swap", replayed=len(pb["mutlog"]),
                      compactions=self.compactions)
        for op, row in pb["mutlog"]:
            if op == "i":
                if not self._churn.note_insert(row, self._ids[row]):
                    # replay overflow (log larger than a fresh slab) —
                    # correctness over latency: full rebuild.  The swap
                    # was already counted above; without the flag the
                    # partially-replayed view's pending entries made
                    # _touch book the SAME event a second time
                    # (ADVICE r5 finding 2).
                    self._touch(count_compaction=False)
                    return True
            else:
                self._churn.note_evict(row)
        return True

    def _tomb_limit(self) -> int:
        ch = self._churn
        n = ch.n_base if ch is not None else 0
        return max(TOMB_MIN, n // TOMB_FRAC)

    def _delta_growth_limit(self) -> int:
        """Overflow headroom: the delta may double up to 8× its
        configured capacity while a background compaction is pending."""
        return 8 * self._delta_cap

    def _absorb_insert(self, row: int) -> None:
        """A slab row became live.  Absorbed into the churn delta when a
        'reachable' base view is active (``_version`` untouched — the
        change is *in* the view); otherwise full invalidation.  A full
        delta no longer stalls anything: the slab doubles (bounded) and
        a background compaction starts, with the old view serving every
        lookup exactly until the new base is ready."""
        ch = self._churn
        if ch is not None and self._snap is not None:
            if self._pending_base is not None:
                self._pending_base["mutlog"].append(("i", row))
            if ch.note_insert(row, self._ids[row]):
                return
            if ch.delta_ids_np.shape[0] < self._delta_growth_limit():
                ch.grow_delta()
                self._start_compaction()
                if ch.note_insert(row, self._ids[row]):
                    return
        self._touch()                   # growth exhausted / no churn view

    def _absorb_evict(self, row: int) -> None:
        """A slab row left the live set (evicted or expired)."""
        ch = self._churn
        if ch is not None and self._snap is not None:
            if self._pending_base is not None:
                self._pending_base["mutlog"].append(("e", row))
            ch.note_evict(row)
            if ch.tomb_count > self._tomb_limit():
                # compaction due (perf policy) — built in the background
                self._start_compaction()
            return
        self._touch()

    def insert(self, node_id: InfoHash, addr: Any, now: Optional[float] = None,
               *, confirm: int = 0) -> Optional[int]:
        """Learn about a peer (↔ Dht::onNewNode/RoutingTable::onNewNode,
        src/routing_table.cpp:204-262).

        confirm: 0 = hearsay (from another node's reply blob),
                 1 = sent us a query, 2 = replied to us.
        Returns the row, or None if the bucket is full of live nodes (the
        peer is kept as the bucket's cached candidate instead).
        """
        if now is None:
            now = time.monotonic()
        key = bytes(node_id)
        if key == bytes(self.self_id):
            return None
        row = self._row_of.get(key)
        if row is not None:
            self._time_seen[row] = now
            if confirm >= 2:
                if self._expired[row]:
                    # revival: the row is dead in every view (its base
                    # copy, if any, was tombstoned when it expired) —
                    # re-enters as a delta insert
                    self._expired[row] = False
                    self._absorb_insert(row)
                elif self._time_reply[row] == 0:
                    # first reply: 'reachable' membership is unchanged
                    # (the row was already in that view), but a cached
                    # 'good'-mask snapshot goes stale
                    if self._snap is not None \
                            and self._snap.mask_key[0] == "good":
                        self._touch()
                self._time_reply[row] = now
                self._auth_err[row] = 0
            if addr is not None:
                self._addrs[row] = addr
            return row

        b = min(InfoHash.common_bits(self.self_id, node_id), radix.MAX_BUCKET)
        if self._bucket_count[b] >= self.k:
            # replace an expired node in this bucket if any
            rows = np.nonzero(self._valid & (self._bucket == b) & self._expired)[0]
            if len(rows) == 0:
                # bucket full of live nodes: keep as replacement candidate
                self._cached[b] = (key, addr)
                return None
            self._evict_row(int(rows[0]))

        if not self._free:
            self._grow()
        row = self._free.pop()
        self._ids[row] = IK.ids_from_bytes(key)
        self._valid[row] = True
        self._expired[row] = False
        self._auth_err[row] = 0
        self._time_seen[row] = now
        self._time_reply[row] = now if confirm >= 2 else 0.0
        self._bucket[row] = b
        self._addrs[row] = addr
        self._row_of[key] = row
        self._bucket_count[b] += 1
        self._absorb_insert(row)
        return row

    def _evict_row(self, row: int) -> None:
        key = self._ids[row:row + 1]
        kb = IK.ids_to_bytes(key).tobytes()
        self._row_of.pop(kb, None)
        self._bucket_count[self._bucket[row]] -= 1
        self._valid[row] = False
        self._addrs[row] = None
        self._free.append(row)
        self._absorb_evict(row)

    def remove(self, node_id: InfoHash) -> None:
        row = self._row_of.get(bytes(node_id))
        if row is not None:
            self._evict_row(row)
            # promote the bucket's cached candidate, if one is waiting
            b = min(InfoHash.common_bits(self.self_id, node_id), radix.MAX_BUCKET)
            cand = self._cached.pop(b, None)
            if cand is not None:
                self.insert(InfoHash(cand[0]), cand[1])

    def on_reply(self, node_id: InfoHash, now: Optional[float] = None) -> None:
        """Peer answered a request (↔ Node::received)."""
        self.insert(node_id, None, now, confirm=2)

    def on_expired(self, node_id: InfoHash) -> None:
        """Request to the peer timed out 3× (↔ Node::setExpired via
        NetworkEngine timeouts, src/request.h:108-112)."""
        row = self._row_of.get(bytes(node_id))
        if row is not None and not self._expired[row]:
            self._expired[row] = True
            self._absorb_evict(row)

    def on_auth_error(self, node_id: InfoHash) -> None:
        """Crypto failure from this peer; 3 strikes expire it (node.h:73-77)."""
        row = self._row_of.get(bytes(node_id))
        if row is not None:
            self._auth_err[row] += 1
            if self._auth_err[row] >= MAX_AUTH_ERRORS \
                    and not self._expired[row]:
                self._expired[row] = True
                self._absorb_evict(row)

    def clear_bad(self) -> None:
        """Drop expired nodes (↔ NodeCache::clearBadNodes on connectivity
        change, src/node_cache.cpp:76-85)."""
        for row in np.nonzero(self._valid & self._expired)[0]:
            self._evict_row(int(row))

    def bulk_load(self, ids_u32: np.ndarray, now: float = 0.0,
                  *, replied: bool = True, addrs=None,
                  buckets=None) -> None:
        """Fill the slab from an [N,5] uint32 id matrix (simulation-scale
        path: no per-row dict bookkeeping, buckets computed on device).
        ``addrs``: optional per-row address (sequence aligned to rows, or
        one address shared by all) so loaded rows are servable in
        closest-node replies (benchmarks/live_node_scale.py).
        ``buckets``: optional precomputed ``common_bits(self, id)`` per
        row — callers loading many small tables (the converged-cluster
        seeder, testing/virtual_net.py) pass it to skip the per-call
        device dispatch of ``radix.bucket_of``.

        Ids already LIVE in the table and batch-internal duplicates are
        dropped: live ids must stay unique across base and delta
        (note_insert's precondition — a duplicate would otherwise appear
        twice in a top-k result through the churn merge).  Known ids
        that have EXPIRED are not dropped: with ``replied=True`` (the
        default) they revive exactly as ``insert(confirm=2)`` would —
        address, reply clock, auth strikes and all (``_row_of`` also
        holds expired rows, so the old skip left a re-seeded peer
        permanently dead — ADVICE r5 finding 3); with
        ``replied=False`` the re-sighting is hearsay and, as in
        ``insert(confirm=0)``, refreshes only ``time_seen`` and the
        address."""
        ids_u32 = np.asarray(ids_u32, dtype=np.uint32)
        raw = IK.ids_to_bytes(ids_u32)
        per_row_addrs = isinstance(addrs, (list, tuple, np.ndarray))
        seen: set = set()
        keep: list = []
        for i in range(ids_u32.shape[0]):
            kb = raw[i].tobytes()
            if kb in seen:
                continue
            row = self._row_of.get(kb)
            if row is not None:
                # known id: refresh it the way insert() would — clocks
                # and address — and revive it if expired
                self._time_seen[row] = now
                if addrs is not None:
                    self._addrs[row] = addrs[i] if per_row_addrs else addrs
                if replied:
                    if self._expired[row]:
                        # revival (↔ insert confirm=2): dead in every
                        # view, re-enters as a delta insert
                        self._expired[row] = False
                        self._absorb_insert(row)
                    elif self._time_reply[row] == 0 \
                            and self._snap is not None \
                            and self._snap.mask_key[0] == "good":
                        # first reply: a cached 'good'-mask snapshot
                        # goes stale (same rule as insert())
                        self._touch()
                    self._time_reply[row] = now
                    self._auth_err[row] = 0
                continue
            seen.add(kb)
            keep.append(i)
        if len(keep) != ids_u32.shape[0]:
            if per_row_addrs:
                addrs = [addrs[i] for i in keep]
            if buckets is not None:
                buckets = np.asarray(buckets)[keep]
            ids_u32 = ids_u32[keep]
            raw = raw[keep]
        n = ids_u32.shape[0]
        if n == 0:
            return
        while self._cap < len(self) + n:
            self._grow()
        rows = np.array([self._free.pop() for _ in range(n)], dtype=np.int64)
        self._ids[rows] = ids_u32
        self._valid[rows] = True
        self._expired[rows] = False
        self._auth_err[rows] = 0
        self._time_seen[rows] = now
        self._time_reply[rows] = now if replied else 0.0
        if buckets is not None:
            b = np.minimum(np.asarray(buckets), radix.MAX_BUCKET)
        else:
            b = np.asarray(radix.bucket_of(jnp.asarray(self.self_limbs),
                                           jnp.asarray(ids_u32)))
        self._bucket[rows] = b.astype(np.int16)
        np.add.at(self._bucket_count, b, 1)
        for i, row in enumerate(rows):
            self._row_of[raw[i].tobytes()] = int(row)
            if addrs is not None:
                self._addrs[int(row)] = addrs[i] if per_row_addrs else addrs
        if self._churn is not None and self._snap is not None \
                and self._churn.n_delta + n <= self.delta_capacity:
            # through _absorb_insert, NOT note_insert directly: a
            # pending background compaction must see these rows in its
            # mutation log or they would vanish from the serving view
            # at swap (found by review; pinned in test_table_churn.py)
            for row in rows:
                self._absorb_insert(int(row))
        else:
            self._touch()

    # --------------------------------------------------------------- reads
    def get_view(self, row: int) -> NodeView:
        return NodeView(
            row=row,
            id=InfoHash(IK.ids_to_bytes(self._ids[row]).tobytes()),
            addr=self._addrs[row],
            time_reply=float(self._time_reply[row]),
            time_seen=float(self._time_seen[row]),
            expired=bool(self._expired[row]),
        )

    def row_of(self, node_id: InfoHash) -> Optional[int]:
        return self._row_of.get(bytes(node_id))

    def addr_of(self, row: int):
        return self._addrs[row]

    def id_of(self, row: int) -> InfoHash:
        return InfoHash(IK.ids_to_bytes(self._ids[row]).tobytes())

    def ids_of_rows(self, rows: np.ndarray) -> list:
        """Vectorized :meth:`id_of` over an int array (-1 → None): ONE
        ids_to_bytes pass instead of a numpy round-trip per row — the
        per-row form measured ~2 ms each on a 1-core host, which made
        materializing a 4096×8 batched-resolve result 66 s
        (benchmarks/live_node_scale.py)."""
        rows = np.asarray(rows).reshape(-1)
        raw = IK.ids_to_bytes(self._ids[np.clip(rows, 0, None)])
        return [InfoHash(raw[i].tobytes()) if r >= 0 else None
                for i, r in enumerate(rows)]

    @property
    def delta_capacity(self) -> int:
        return self._delta_cap

    @property
    def churn_pending(self) -> int:
        """Mutations absorbed by the churn view since the last base
        build (tombstones + delta inserts).  0 ⇒ the base snapshot is
        complete."""
        return self._churn.pending if self._churn is not None else 0

    def snapshot(self, now: Optional[float] = None, *,
                 mask: str = "reachable") -> Snapshot:
        """Full device snapshot for batched queries.  mask: 'reachable'
        (valid & not expired), 'good', or 'valid'.  Cached until the
        table mutates (liveness masks additionally keyed by a 10 s time
        bucket).  Pending churn (delta inserts / tombstones) forces a
        rebuild here — this is the compaction point; lookups that can
        use the incremental view go through :meth:`view` instead."""
        if now is None:
            now = time.monotonic()
        if mask == "reachable":
            self._maybe_swap(force=True)
        tkey = int(now // 10) if mask == "good" else 0
        mk = (mask, tkey)
        if self._snap is not None and self._snap.version == self._version \
                and self._snap.mask_key == mk and self.churn_pending == 0:
            return self._snap
        if mask == "good":
            m = self.good_mask(now)
        elif mask == "valid":
            m = self._valid
        else:
            m = self.reachable_mask(now)
        # count a *compaction* only when this rebuild folds pending
        # churn (delta inserts / tombstones) back into the base — plain
        # first builds and mask-flavor rebuilds are not compactions
        if self.churn_pending > 0:
            self.compactions += 1
            _M_COMPACTIONS.inc()
        sorted_ids, perm, n_valid = sort_table(
            jnp.asarray(self._ids), jnp.asarray(m)
        )
        self._snap = Snapshot(sorted_ids, perm, n_valid, self._version, mk)
        # churn absorption only tracks the 'reachable' mask — the one
        # every routing lookup uses.  'good'/'valid' snapshots rebuild
        # on mutation as before.
        self._churn = ChurnView(self._snap, self._cap, self._delta_cap) \
            if mask == "reachable" else None
        return self._snap

    def view(self, now: Optional[float] = None, *, mask: str = "reachable"):
        """Lookup view: the O(1)-mutation churn view while deltas or
        tombstones are pending, else the plain snapshot.  Both expose
        ``lookup(queries, k=, window=)`` with identical (exact)
        results; the churn view skips the full re-sort + re-expand a
        mutation would otherwise cost (SURVEY §7 incremental updates)."""
        if mask == "reachable":
            self._maybe_swap()           # install a finished compaction
        ch = self._churn
        if ch is not None and self._snap is not None and ch.pending \
                and self._snap.mask_key == (mask, 0):
            return ch
        return self.snapshot(now, mask=mask)

    def find_closest(self, targets, *, k: int = TARGET_NODES,
                     now: Optional[float] = None, mask: str = "reachable",
                     window: int = 128, mesh=None, layout=None):
        """k closest known peers for each target id
        (↔ RoutingTable::findClosestNodes, src/routing_table.cpp:109-150 —
        but batched over Q targets in one device call).

        targets: [Q,5] uint32, [Q,20] uint8, bytes, or list of InfoHash.
        Returns (rows [Q,k] int32, dist [Q,k,5] uint32) numpy, -1 padded.

        Small tables × small batches (the live protocol regime) take an
        exact host scan over the slab — no snapshot, no device call, no
        compile; results are bit-identical to the device path (live ids
        are unique, so XOR distances never tie and the order is fully
        determined).  Large tables or big query waves go through
        :meth:`view` (device snapshot / churn kernels); a ``mesh``
        (``config.resolve_mesh_t``) row-shards the snapshot resolve
        over its ``t`` axis (:meth:`Snapshot.lookup`) — the churn view
        and the host scan ignore it (identical results either way).
        A reshard ``layout`` (ISSUE-17) moves the sharded split to
        traffic-weighted boundaries — same results, rebalanced load.
        """
        return self.find_closest_launch(targets, k=k, now=now, mask=mask,
                                        window=window, mesh=mesh,
                                        layout=layout).consume()

    def find_closest_launch(self, targets, *, k: int = TARGET_NODES,
                            now: Optional[float] = None,
                            mask: str = "reachable", window: int = 128,
                            mesh=None, layout=None) -> PendingLookup:
        """Async form of :meth:`find_closest` (round-20 wave pipeline):
        returns a :class:`PendingLookup` whose device kernel is already
        in flight; ``consume()`` blocks and maps rows.  The host-scan
        fast path returns an already-resolved handle (``ready()`` is
        immediately True — the live-protocol regime never defers)."""
        q = _as_limbs(targets)
        q = q.reshape(-1, IK.N_LIMBS)
        # truth flag for the spans/counters upstream: whether THIS
        # resolve actually ran the t-sharded kernel (the host scan and
        # the churn view ignore mesh) — read by
        # Dht.find_closest_nodes_launch right after the call, same
        # thread (the DHT loop is single-threaded)
        self.last_resolve_sharded = False
        if len(self) <= HOST_SCAN_MAX_ROWS \
                and q.shape[0] <= HOST_SCAN_MAX_QUERIES:
            return PendingLookup.resolved(
                *self._find_closest_host(q, k, now, mask))
        view = self.view(now, mask=mask)
        if mesh is not None and mesh.shape.get("t", 1) > 1 \
                and isinstance(view, Snapshot):
            self.last_resolve_sharded = True
            return view.lookup_launch(q, k=k, window=window, mesh=mesh,
                                      layout=layout)
        return view.lookup_launch(q, k=k, window=window)

    def _find_closest_host(self, q: np.ndarray, k: int,
                           now: Optional[float], mask: str):
        """Exact numpy top-k over the live slab rows (host fast path)."""
        if now is None:
            now = time.monotonic()
        if mask == "good":
            m = self.good_mask(now)
        elif mask == "valid":
            m = self._valid
        else:
            m = self.reachable_mask(now)
        rows = np.nonzero(m)[0]
        Qn = q.shape[0]
        out_rows = np.full((Qn, k), -1, dtype=np.int32)
        out_dist = np.full((Qn, k, IK.N_LIMBS), 0xFFFFFFFF, dtype=np.uint32)
        if len(rows):
            d = self._ids[rows][None, :, :] ^ q[:, None, :]    # [Q, n, 5]
            for i in range(Qn):
                # lexicographic 160-bit ordering: np.lexsort's LAST key
                # is primary (limb 0), matching InfoHash::xorCmp
                order = np.lexsort(
                    (d[i, :, 4], d[i, :, 3], d[i, :, 2],
                     d[i, :, 1], d[i, :, 0]))[:k]
                out_rows[i, :len(order)] = rows[order]
                out_dist[i, :len(order)] = d[i, order]
        return out_rows, out_dist

    # --------------------------------------------------------- maintenance
    def bucket_occupancy(self) -> np.ndarray:
        return self._bucket_count.copy()

    def stale_buckets(self, now: float, age: float = NODE_EXPIRE_TIME) -> np.ndarray:
        """Occupied buckets with no *reply* within `age` seconds — incl.
        never-replied buckets, which the reference marks stale from birth
        (Bucket::time = time_point::min(); bucketMaintenance's 10-min
        rule, src/dht.cpp:1780-1838, src/routing_table.cpp:210-211).
        Computed by the device compare-and-reduce (ops/radix.py
        bucket_last_seen, which owns the never-replied semantics — the
        host ``np.maximum.at`` duplicate this replaced diverged from it)."""
        last = np.asarray(radix.bucket_last_seen(
            jnp.asarray(self.self_limbs), jnp.asarray(self._ids),
            jnp.asarray(self._valid), jnp.asarray(self._time_reply)))
        occupied = self._bucket_count > 0
        return np.nonzero(occupied & (last < now - age))[0]

    def _next_maint_key(self):
        """Thread the table's reusable maintenance PRNG key (minted once
        at construction; split per use — no fresh PRNGKey per tick)."""
        if self._maint_key is None:
            self._maint_key = jax.random.PRNGKey(
                int.from_bytes(os.urandom(4), "big"))
        self._maint_key, sub = jax.random.split(self._maint_key)
        return sub

    def maintenance_sweep(self, now: float, age: float = NODE_EXPIRE_TIME,
                          key=None):
        """ONE fused device pass over the slab: occupancy, per-bucket
        last-reply staleness (never-replied ⇒ stale from birth), and a
        refresh target inside every stale bucket
        (↔ Dht::bucketMaintenance, src/dht.cpp:1780-1838 +
        RoutingTable::randomId) — replaces the stale_buckets +
        refresh_targets pair with a single launch.

        Returns ``(stale, targets)``: stale bucket indices [B] int64 and
        their refresh ids [B, 5] uint32."""
        counts, _last, stale, targets = radix.maintenance_sweep(
            jnp.asarray(self.self_limbs), jnp.asarray(self._ids),
            jnp.asarray(self._valid), jnp.asarray(self._time_reply),
            now, age, key if key is not None else self._next_maint_key())
        stale = np.nonzero(np.asarray(stale))[0]
        return stale, np.asarray(targets)[stale]

    def refresh_targets(self, buckets, key=None) -> np.ndarray:
        """Random lookup target inside each given bucket (↔
        RoutingTable::randomId, src/routing_table.cpp:67-85).  → [B,5].
        With ``key=None`` the table's reusable maintenance key is
        threaded (split per call) instead of minting a fresh PRNGKey."""
        out = radix.random_id_in_bucket(
            jnp.asarray(self.self_limbs), jnp.asarray(np.asarray(buckets)),
            key if key is not None else self._next_maint_key()
        )
        return np.asarray(out)

    def network_size_estimate(self) -> int:
        return int(radix.estimate_network_size(
            jnp.asarray(self.self_limbs), jnp.asarray(self._ids),
            jnp.asarray(self._valid), k=self.k,
        ))

    def export_nodes(self, now: Optional[float] = None) -> list:
        """Good nodes for persistence/bootstrap (↔ Dht::exportNodes,
        src/dht.cpp:2029-2059)."""
        if now is None:
            now = time.monotonic()
        rows = np.nonzero(self.good_mask(now))[0]
        return [(self.id_of(int(r)), self._addrs[int(r)]) for r in rows]


def _as_limbs(targets) -> np.ndarray:
    if isinstance(targets, (bytes, bytearray)):
        return IK.ids_from_bytes(targets)
    if isinstance(targets, (list, tuple)):
        return IK.ids_from_hashes(targets)
    arr = np.asarray(targets)
    if arr.dtype == np.uint8:
        return IK.ids_from_bytes(arr)
    return arr.astype(np.uint32)
