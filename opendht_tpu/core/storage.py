"""Per-key value storage with quota accounting (reference src/storage.h).

- :class:`StorageBucket` — per-IP usage tracker; the eviction policy
  drops the oldest-expiring value of the largest consumer
  (storage.h:33-56, used by Dht.expireStore dht.cpp:1299-1348).
- :class:`ValueStorage` — one stored value + created/expiration times.
- :class:`Storage` — the per-InfoHash store: refresh-or-insert with size
  diffs (storage.h:181-220), expiry partition returning the expired
  values for listener notification (storage.h:248-286), and both local
  and remote listener maps.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..infohash import InfoHash
from .listener import Listener, LocalListener
from .value import Filter, Filters, Value

#: remote listeners expire with node liveness (node.h:151: 10 min)
NODE_EXPIRE_TIME = 10 * 60.0

MAX_VALUES = 1024                    # storage.h:77


class StorageBucket:
    """Usage ledger for one IP (or range): total bytes + an
    expiration-ordered index of (key, value id) for eviction."""

    __slots__ = ("_entries", "_total")

    def __init__(self):
        # sorted-by-expiration list of (expiration, key, vid, size)
        self._entries: List[Tuple[float, InfoHash, int, int]] = []
        self._total = 0

    def insert(self, key: InfoHash, value: Value, expiration: float) -> None:
        sz = value.size()
        self._total += sz
        bisect.insort(self._entries, (expiration, key, value.id, sz),
                      key=lambda e: e[0])

    def erase(self, key: InfoHash, value: Value, expiration: float) -> None:
        # entries are expiration-sorted: scan only the equal-expiration run
        entries = self._entries
        i = bisect.bisect_left(entries, expiration, key=lambda e: e[0])
        while i < len(entries) and entries[i][0] == expiration:
            _, k, vid, sz = entries[i]
            if k == key and vid == value.id:
                del entries[i]
                self._total -= sz
                return
            i += 1

    @property
    def size(self) -> int:
        return self._total

    def get_oldest(self) -> Optional[Tuple[InfoHash, int]]:
        """(key, value id) of the earliest-expiring entry (storage.h:52)."""
        if not self._entries:
            return None
        _, k, vid, _ = self._entries[0]
        return k, vid


@dataclass
class ValueStorage:
    """(storage.h:58-68)"""
    data: Value
    created: float
    expiration: float
    store_bucket: Optional[StorageBucket] = None


@dataclass
class StoreDiff:
    """Net effect of a storage op (storage.h:80-90)."""
    size_diff: int = 0
    values_diff: int = 0
    listeners_diff: int = 0


class Storage:
    """All state stored under one InfoHash."""

    def __init__(self, now: float = 0.0):
        self.maintenance_time = now          # next republish sweep
        # armed by Dht.storage_store on a maintain_storage node (the
        # reference schedules dataPersistence only there, dht.cpp:
        # 1193-1228); listen-created storages are NEVER maintenance-
        # swept — the round-10 calendar checks this flag
        self.maintenance_armed = False
        self.values: List[ValueStorage] = []
        self.total_size = 0
        # remote listeners: node -> {socket id -> Listener}
        self.listeners: Dict[object, Dict[int, Listener]] = {}
        self.local_listeners: Dict[int, LocalListener] = {}
        self.listener_token = 1

    # -- reads -------------------------------------------------------------
    def empty(self) -> bool:
        return not self.values

    def value_count(self) -> int:
        return len(self.values)

    def get_by_id(self, vid: int) -> Optional[Value]:
        for vs in self.values:
            if vs.data.id == vid:
                return vs.data
        return None

    def get(self, f: Optional[Filter] = None) -> List[Value]:
        return Filters.apply(f, (vs.data for vs in self.values))

    # -- writes ------------------------------------------------------------
    def store(self, key: InfoHash, value: Value, created: float,
              expiration: float, bucket: Optional[StorageBucket] = None
              ) -> Tuple[Optional[ValueStorage], StoreDiff]:
        """Refresh-or-insert (storage.h:181-220).  Returns (slot, diff);
        slot is None when nothing changed (same object refreshed, or the
        MAX_VALUES cap was hit)."""
        for vs in self.values:
            if vs.data is value or vs.data.id == value.id:
                vs.created = created
                if vs.data is value:
                    # same object re-stored: expiration must track the new
                    # created, or later refresh() calls (which derive the
                    # ttl from expiration-created) extend by a shrunken ttl
                    if vs.store_bucket:
                        vs.store_bucket.erase(key, vs.data, vs.expiration)
                        vs.store_bucket.insert(key, vs.data, expiration)
                    vs.expiration = expiration
                    return None, StoreDiff()
                size_diff = value.size() - vs.data.size()
                if vs.store_bucket:
                    vs.store_bucket.erase(key, vs.data, vs.expiration)
                vs.expiration = expiration
                vs.store_bucket = bucket
                if bucket:
                    bucket.insert(key, value, expiration)
                vs.data = value
                self.total_size += size_diff
                return vs, StoreDiff(size_diff, 0, 0)
        if len(self.values) >= MAX_VALUES:
            return None, StoreDiff()
        sz = value.size()
        vs = ValueStorage(value, created, expiration, bucket)
        self.values.append(vs)
        self.total_size += sz
        if bucket:
            bucket.insert(key, value, expiration)
        return vs, StoreDiff(sz, 1, 0)

    def refresh(self, now: float, vid: int, key: InfoHash
                ) -> Optional[float]:
        """Restart a value's lifetime (storage.h:159-166).  The reference
        recomputes expiry from ``created`` at sweep time; we cache the
        absolute expiration, so the refresh must extend it (and re-index
        the per-IP quota bucket, which is expiration-sorted).

        Returns the new absolute expiration (the caller must schedule an
        expiry sweep at that time), or None if the value is unknown."""
        for vs in self.values:
            if vs.data.id == vid:
                ttl = vs.expiration - vs.created
                if vs.store_bucket is not None:
                    vs.store_bucket.erase(key, vs.data, vs.expiration)
                vs.created = now
                vs.expiration = now + ttl
                if vs.store_bucket is not None:
                    vs.store_bucket.insert(key, vs.data, vs.expiration)
                return vs.expiration
        return None

    def remove(self, key: InfoHash, vid: int) -> StoreDiff:
        """(storage.h:222-238)"""
        for i, vs in enumerate(self.values):
            if vs.data.id == vid:
                if vs.store_bucket:
                    vs.store_bucket.erase(key, vs.data, vs.expiration)
                sz = vs.data.size()
                del self.values[i]
                self.total_size -= sz
                return StoreDiff(-sz, -1, 0)
        return StoreDiff()

    def clear(self, key: "InfoHash | None" = None) -> StoreDiff:
        """(storage.h:240-247).  Pass the storage key so quota-tracked
        values are also unlinked from their per-IP StorageBucket; without
        it the buckets would keep phantom entries and break eviction."""
        if key is not None:
            for vs in self.values:
                if vs.store_bucket:
                    vs.store_bucket.erase(key, vs.data, vs.expiration)
        d = StoreDiff(-self.total_size, -len(self.values), 0)
        self.values.clear()
        self.total_size = 0
        return d

    def expire(self, key: InfoHash, now: float) -> Tuple[int, List[Value]]:
        """Drop expired values and stale remote listeners; returns
        (size_diff, expired values) so the caller can notify listeners
        (storage.h:248-286)."""
        for node in list(self.listeners):
            node_listeners = self.listeners[node]
            for sid in list(node_listeners):
                if node_listeners[sid].time + NODE_EXPIRE_TIME < now:
                    del node_listeners[sid]
            if not node_listeners:
                del self.listeners[node]

        keep, expired = [], []
        size_diff = 0
        for vs in self.values:
            if vs.expiration > now:
                keep.append(vs)
            else:
                size_diff -= vs.data.size()
                if vs.store_bucket:
                    vs.store_bucket.erase(key, vs.data, vs.expiration)
                expired.append(vs.data)
        self.values = keep
        self.total_size += size_diff
        return size_diff, expired
