"""Batched iterative Kademlia lookup engine.

The reference resolves each ``get()`` with a sequential state machine:
``Dht::searchStep`` (src/dht.cpp:561-654) keeps a sorted set of ≤ 14
candidates per target (``Search::insertNode``, src/search.h:636-722),
keeps α = 4 requests in flight (dht.h:321), inserts every reply's nodes
back into the set, and is done when the first k = 8 candidates have all
replied (``isSynced``, src/search.h:734-747).

Here the *entire population of concurrent lookups* advances together:
one device step selects the next α unqueried candidates for every one of
Q searches, resolves all Q·α simulated replies against the global node
matrix, and merges them back — all as fixed-shape array ops inside a
``lax.while_loop``.  A million lookups cost a few dozen fused device
steps instead of millions of scalar iterations.  As of round 6 the
steady-state round is ROUND-FUSED: all α·k reply rows of the whole wave
are fetched by ONE fused gather (``ops.sorted_table.fused_gather_planar``
over a single [W·α·k] index vector), the reply blocks are positioned
from the *carried* candidate distance limb instead of a per-round peer
gather, and both LUT block edges ride one stacked read — so a round's
serial chain is one gather + one LUT read + two merge sorts, the
minimum issue structure the reply model admits (see PARITY.md for the
measured wave-latency bound that follows).

State layout (fixed shapes; "no candidate" = node index -1):

    cand_node [Q, S]     int32   sorted-table index of each candidate
    cand_l    5×[Q, S]   uint32  XOR distance limb planes (sort key;
                                 kept planar — see layout note below)
    queried   [Q, S]     int32   request sent
    replied   [Q, S]     int32   reply merged
    hops      [Q]        int32   rounds taken until convergence
    done      [Q]        bool

Simulated network model (for hop-count/convergence studies, mirroring
the role of the reference's netns cluster harness,
python/tools/dht/tests.py): node x, asked for target t, answers with k
nodes drawn from the prefix block sharing ``commonBits(x, t) + 1``
leading bits with t — exactly what x's deepest relevant k-bucket holds
in a converged Kademlia network (every hop gains ≥ 1 prefix bit, ~3 in
expectation with k = 8 samples).  When that block is smaller than k the
reply is the k rows straddling t's sorted position — the closest set a
real peer that close would answer with (model validated against the
live protocol path at matched N, tests/test_hop_parity.py).  Replies
are deterministic in (seed, round, search, slot) via a counter-based
hash, so runs are reproducible and shardable.

This module is the *simulation* engine (hop-count / convergence
studies over the synthetic reply model).  The LIVE serving path's
batched-resolve seam is ``runtime.dht.Dht.find_closest_nodes_launch``
→ ``core.table.NodeTable.find_closest_launch`` →
``core.table.Snapshot.lookup_launch`` — since round 20 every layer of
that chain returns a launch handle (``core.table.PendingLookup`` /
``runtime.dht.BatchedResolve``) whose ``consume()`` materializes the
result, so ``runtime/wave_builder.py`` can keep ``ingest_pipeline_depth``
≥ 2 waves in flight while the simulation engine here stays a
synchronous whole-population ``lax.while_loop``.
"""

from __future__ import annotations

import functools
import math
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..ops.ids import N_LIMBS, ID_BITS, ids_to_bytes, clz32
from ..ops.radix import _PREFIX_MASKS
from ..ops.sorted_table import (_lex_lt, _lower_bound, _lut_bits,
                                build_prefix_lut, default_lut_bits,
                                fused_gather_planar, lut_budget_steps)

_U32 = jnp.uint32

ALPHA = 4            # in-flight requests per search (dht.h:321)
SEARCH_NODES = 14    # candidate set size (dht.h:308)
TARGET_NODES = 8     # convergence set (routing_table.h:26)


def _mix32(x):
    """Counter-based uint32 hash (splitmix-style) for reply sampling."""
    x = x.astype(_U32)
    x = x ^ (x >> 16)
    x = x * _U32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * _U32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def _increment(ids):
    """160-bit +1 over [..., 5] uint32 limbs (wraps to zero)."""
    out = []
    carry = jnp.ones(ids.shape[:-1], dtype=_U32)
    for i in range(N_LIMBS - 1, -1, -1):
        s = ids[..., i] + carry
        carry = jnp.where((s == 0) & (carry == 1), _U32(1), _U32(0))
        out.append(s)
    return jnp.stack(out[::-1], axis=-1)


def _prefix_block_bounds(lower, n, targets, prefix_len):
    """[lo, ub) sorted-index range of ids sharing `prefix_len` leading bits
    with each target.  ``lower``: flat [M,5] → [M] lower-bound positions;
    targets [..., 5]; prefix_len [...] int32.

    Both block edges go through ONE batched ``lower`` call: the search
    is a fixed number of SEQUENTIAL gather steps, so two M-row calls
    cost twice the serial latency of one 2M-row call (per-element
    gathers are issue-bound, and each step's gather is latency-, not
    bandwidth-, limited at these sizes)."""
    masks = jnp.take(jnp.asarray(_PREFIX_MASKS),
                     jnp.clip(prefix_len, 0, ID_BITS), axis=0)
    p_lo = targets & masks
    p_hi_inc = _increment(p_lo | ~masks)
    both = jnp.concatenate([p_lo.reshape(-1, N_LIMBS),
                            p_hi_inc.reshape(-1, N_LIMBS)], axis=0)
    pos = lower(both)
    M = both.shape[0] // 2
    lo = pos[:M].reshape(targets.shape[:-1])
    ub = pos[M:].reshape(targets.shape[:-1])
    # p_hi of all-ones wraps to zero on increment → block extends to n
    wrapped = jnp.all(p_hi_inc == 0, axis=-1)
    ub = jnp.where(wrapped, n, ub)
    return lo, ub


def _lut_block_bounds(lut, t0, prefix_len):
    """[lo, ub) sorted-index range of ids sharing ``prefix_len`` leading
    bits with targets whose FIRST LIMB is ``t0`` — as two LUT reads, no
    binary search.

    ``build_prefix_lut``'s entry p is the count of valid rows with
    top-``bits`` prefix < p, so for any prefix length L ≤ bits the block
    edges are EXACT on any table: lo = lut[pfx], ub = lut[pfx + 2^(bits−L)]
    (the +1 sentinel entry covers the all-ones wrap).  Deeper prefixes
    clamp to their containing LUT bucket — an over-approximation whose
    only observable effect is the reply model's ``size ≥ k`` branch: at
    the default ~1-row buckets (default_lut_bits) a clamped bucket is
    ~never ≥ k rows, so both the exact and clamped computations take
    the near-target fallback window and the trajectory is unchanged
    (measured: hop distribution and convergence identical at 10M).

    This removes the per-round batched binary search that the round-body
    attribution (benchmarks/exp_round_r5.py) measured at 8.6 of the
    10.1 ms round — the round-5 engine win.  The sharded twin computes
    the same values as a psum of per-shard LUT reads (global lower
    bound = Σ shard-local counts), so tp/single-device bit-identity is
    preserved (tests/test_sharded.py).
    """
    bits = _lut_bits(lut)
    Lc = jnp.clip(prefix_len, 0, bits)
    shift = (jnp.int32(bits) - Lc).astype(_U32)
    top = (t0 >> _U32(32 - bits)).astype(_U32)
    pfx = (top >> shift) << shift
    # ONE stacked take for both edges: LUT reads are per-element
    # issue-bound gathers like every other table access in the round,
    # so what matters is the number of gather ops on the serial chain —
    # fusing lo and ub into a single [2, ...] index vector halves it
    # (and in the sharded twin the psum over the stacked pair is ONE
    # collective per round instead of two — parallel/sharded.py).
    edges = jnp.stack([pfx, pfx + (_U32(1) << shift)]).astype(jnp.int32)
    g = jnp.take(lut, edges)
    return g[0], g[1]


def _guarded_lower_bound(sorted_ids, n, lut):
    """Positioning closure: LUT-started bounded search when every LUT
    bucket fits the in-bucket step budget, else the full-depth binary
    search — decided ON DEVICE with one ``lax.cond`` per call site.

    The bounded LUT search is silently wrong when a bucket holds more
    than 2^steps rows (possible only on clustered/adversarial id
    distributions); there is no exactness certificate inside the search
    simulation to catch it, so the guard makes the LUT path *sound*
    rather than merely fast: ``max(diff(lut))`` bounds every bucket, and
    oversized tables simply pay the log2(N)-step search.

    The fast path additionally searches on the TOP 64 BITS only (the
    probe-step gather is per-element issue-bound — ~70% of the whole
    search-sim round was these gathers at 5 limbs) and then restores
    the exact 160-bit answer with ONE full-width compare: when no two
    ADJACENT valid rows share their top 64 bits (checked on device in
    one scan), at most one row can satisfy row64 == q64, so the 160-bit
    lower bound is the 64-bit one plus at most 1 —
    ``lb160 = lb64 + (row[lb64] < q)``.  Tables violating the
    precondition (64-bit duplicate neighbors) take the full 5-limb
    search instead — exactness never depends on probabilistic
    assumptions.
    """
    N = sorted_ids.shape[0]
    # same budget _lower_bound will actually use (ONE shared definition)
    steps = lut_budget_steps(N, _lut_bits(lut))
    # a B-row bucket needs ceil(log2 B)+1 search steps; with `steps`
    # available, buckets up to 2^(steps-1) rows are provably covered
    lut_ok = jnp.max(lut[1:] - lut[:-1]) <= jnp.int32(
        1 << min(steps - 1, 30))
    nn = jnp.asarray(n, jnp.int32)
    s0, s1 = sorted_ids[:, 0], sorted_ids[:, 1]
    if N > 1:
        adj_valid = (jnp.arange(N - 1, dtype=jnp.int32) + 1) < nn
        tie64 = jnp.any((s0[1:] == s0[:-1]) & (s1[1:] == s1[:-1])
                        & adj_valid)
    else:
        tie64 = jnp.bool_(False)
    sorted_t_full = sorted_ids.T

    def fast(q):
        lb = _lower_bound(sorted_ids, q, n, lut=lut, lut_steps=None,
                          limbs=2)
        # exact correction: row[lb] < q is only possible when the row's
        # top 64 bits EQUAL the probe's (the 64-bit search guarantees
        # row64 >= q64), so gather 2 limbs to detect equality and fetch
        # the tail limbs only in that astronomically rare case (a
        # random probe matches some row's 64-bit prefix with
        # probability ~N/2^64) — the common path pays 2/5 of the
        # correction gather
        cl = jnp.clip(lb, 0, N - 1)
        g2 = jnp.take(sorted_t_full[:2], cl, axis=1)
        eq64 = (g2[0] == q[:, 0]) & (g2[1] == q[:, 1]) & (lb < nn)

        def tail_bump(_):
            g3 = jnp.take(sorted_t_full[2:], cl, axis=1)
            lt = _lex_lt(g3, [q[:, l] for l in range(2, N_LIMBS)],
                         N_LIMBS - 2)
            return (eq64 & lt).astype(jnp.int32)

        bump = lax.cond(jnp.any(eq64), tail_bump,
                        lambda _: jnp.zeros_like(lb), operand=None)
        return jnp.minimum(lb + bump, nn)

    def lower(flat):
        # three tiers: 64-bit search + exact correction (needs tie-free
        # top-64 neighbors) → full-limb LUT-bounded search (sound for
        # any data as long as buckets fit the budget) → full-depth
        # un-LUT'd search (always sound)
        return lax.cond(
            lut_ok & ~tie64,
            fast,
            lambda q: lax.cond(
                lut_ok,
                lambda q2: _lower_bound(sorted_ids, q2, n, lut=lut,
                                        lut_steps=None),
                lambda q2: _lower_bound(sorted_ids, q2, n),
                q),
            flat)
    return lower


def _common_bits_planar(a_l, b_l):
    """commonBits over limb-plane lists (same math as ids.common_bits)."""
    out = jnp.full(a_l[0].shape, ID_BITS, dtype=jnp.int32)
    prev_zero = jnp.ones(a_l[0].shape, dtype=bool)
    for i in range(N_LIMBS):
        xi = a_l[i] ^ b_l[i]
        is_first = prev_zero & (xi != 0)
        out = jnp.where(is_first, 32 * i + clz32(xi), out)
        prev_zero = prev_zero & (xi == 0)
    return out


def _lookup_engine(gather_planar, lower, n, targets, q_index, q_total,
                   seed_u, *, k, alpha, search_nodes, max_hops,
                   state_limbs: int = N_LIMBS,
                   compact_after: "int | None" = None,
                   compact_cap: int = 0,
                   block_bounds=None):
    """The iterative-lookup state machine, abstracted over table access.

    ALL access to the (possibly distributed) sorted node table flows
    through two injected primitives, which is what lets the same engine
    run single-device (:func:`simulate_lookups`) and with the table
    row-sharded over a mesh axis (parallel/sharded.py:
    ``tp_simulate_lookups`` — each primitive becomes a shard-local
    partial computation + one ``psum`` over the table axis):

      gather_planar(rows [...]) -> 5×[...] uint32 limb planes of the
          globally-sorted table rows (callers pre-clip to [0, n));
          entries for out-of-range rows may be garbage — every caller
          masks them.
      lower(flat [M, 5]) -> [M] int32 global lower-bound positions.
      block_bounds(t0, prefix_len) -> (lo, ub) prefix-block edges
          (optional third primitive): t0 = targets' first limb
          (broadcastable against prefix_len).  When provided (the
          :func:`_lut_block_bounds` fast path — one stacked LUT read
          for both edges), the per-round positioning search disappears,
          which the round-body attribution measured as 85% of the
          round; when None the engine falls back to the exact search
          via ``lower`` (:func:`_prefix_block_bounds`).

    ROUND-FUSED GATHER (round 6): with ``block_bounds`` provided, the
    steady-state round body issues exactly ONE ``gather_planar`` call —
    the fused [W·α·k] reply-distance fetch inside the merge.  The
    round-5 engine also gathered the α queried peers' top limb each
    round (to position the reply blocks); that value is ``x0 ^ t0`` —
    the very distance limb the candidate state already carries — so it
    now rides the α-selection max-reductions instead (bit-identical;
    tests/test_search.py pins the engine's outputs against committed
    goldens so any reply-stream drift fails loudly).  In the
    table-sharded twin the same change removes one of the per-round
    psum sites (parallel/sharded.py).

    ``q_index``/``q_total`` are each query's GLOBAL index and the global
    batch size — the deterministic reply hash is seeded by global query
    identity, so a sharded run is bit-identical to the unsharded one.

    ``state_limbs`` picks how many distance limbs the candidate state
    carries through the per-round merge sorts: 5 (exact 160-bit
    ordering) or 2 (rank by the top 64 distance bits only — the merge
    sorts move 5 operands instead of 8 and the per-round reply-distance
    gather fetches 2 planes instead of 5; bitwise identical to the
    exact mode unless two distinct candidates tie on their top 64
    distance bits, ~2^-58 per merge at S+R=44 rows).  Either way the
    returned ``dist`` carries all 5 limbs (reconstructed from the final
    node ids in one gather).

    ``compact_after`` (static round count) enables SURVIVOR COMPACTION:
    after that many rounds the (typically small) set of unconverged
    searches is packed into a ``compact_cap``-wide sub-batch on device
    (``jnp.nonzero(size=cap)`` — no host sync) and run to convergence
    at the narrow width, then scattered back; a final full-width loop
    resuming AT THE CUT ROUND is the safety net for cap overflow (it
    runs ZERO iterations when the cap held).  Reply streams are keyed
    by (global query id, round number), so results are bitwise
    identical to the uncompacted run regardless of cap (overflow rows
    replay exactly the rounds they were paused for); the sole
    exception is a row still unconverged at ``max_hops``, which both
    engines report converged=False.
    """
    Q = targets.shape[0]
    S = search_nodes
    R = alpha * k            # reply entries merged per round
    NL = state_limbs

    pos_t_full = lower(targets)                        # [Q], fallback replies

    def reply_gather(tgt, pt, qidx, x_rows, round_no, x_d0=None):
        """Simulated answers of the α queried nodes per search.
        x_rows [W, alpha] int32 (−1 = no request) → node rows [W, R].

        ``x_d0``: the queried peers' top distance limb ``x0 ^ t0``
        carried from the candidate state (the ROUND-FUSED form — see
        the round body), or None to gather it from the table (the
        bootstrap call, whose peer is not a candidate yet)."""
        W = tgt.shape[0]
        if block_bounds is not None:
            # 1-LIMB cb: the LUT block read clamps prefixes at its
            # ≤24-bit width, so any cb ≥ 32 yields the same clamped
            # edges — computing cb from limb 0 alone (exact below 32,
            # 32 for deeper) is BIT-IDENTICAL through the LUT.
            # ROUND-FUSED GATHER (round 6): inside the loop x_d0 comes
            # from the candidate state (cand_l[0] IS x0 ^ t0 — the
            # merge computed it when the peer was first heard of), so
            # the per-round 1-plane peer gather of round 5 (~1 ms of
            # the ~5.5 ms round at W=16K, and one whole psum site in
            # the sharded engine) disappears: the round's ONLY table
            # gather is the fused [W·α·k] reply gather in merge().
            # block_mode="exact" keeps the full-width gathered path.
            if x_d0 is None:
                x0 = gather_planar(x_rows, 1)[0]
                x_d0 = x0 ^ tgt[:, 0:1]
            b = clz32(x_d0)                      # clz32(0) == 32 by contract
            lo, ub = block_bounds(tgt[:, 0:1], b + 1)
        else:
            x_l = gather_planar(x_rows, N_LIMBS)     # full ids: exact cb
            t_l = [tgt[:, l:l + 1] for l in range(N_LIMBS)]
            b = _common_bits_planar(x_l, t_l)                        # [W,a]
            prefix_len = jnp.clip(b + 1, 0, ID_BITS)
            lo, ub = _prefix_block_bounds(lower, n, tgt[:, None, :]
                                          .repeat(x_rows.shape[1], 1),
                                          prefix_len)
        size = jnp.maximum(ub - lo, 0)                                     # [W,a]

        qi = qidx.astype(_U32)[:, None, None]          # GLOBAL query ids
        ai = jnp.arange(x_rows.shape[1], dtype=_U32)[None, :, None]
        ji = jnp.arange(k, dtype=_U32)[None, None, :]
        ctr = (((round_no.astype(_U32) * _U32(q_total) + qi) * _U32(alpha)
                + ai) * _U32(k) + ji) ^ seed_u
        h = _mix32(ctr)                                                     # [W,a,k]

        blk = lo[..., None] + (h % jnp.maximum(size[..., None], 1).astype(_U32)
                               ).astype(jnp.int32)
        # fallback: block too small → the peer knows the target's
        # neighborhood and answers with rows from the (alpha·k)-wide
        # window straddling pos_t, each queried slot contributing a
        # distinct k-slice so one round covers the window determinist-
        # ically (a real node replies with the closest set it knows, not
        # a uniform sample — the round-1 uniform model overestimated
        # terminal hops ~2x; validated against the live protocol path in
        # tests/test_hop_parity.py)
        base = jnp.clip(pt[:, None, None] - R // 2, 0,
                        jnp.maximum(n - R, 0))
        fb = jnp.clip(base + (ai * _U32(k) + ji).astype(jnp.int32), 0,
                      jnp.maximum(n - 1, 0))
        rows = jnp.where((size[..., None] >= k), blk, fb)
        rows = jnp.where((x_rows >= 0)[..., None], rows, -1)
        return rows.reshape(W, R)

    def merge(tgt, cand_node, cand_l, queried, new_rows):
        """Insert replies, dedupe by node, keep the S closest
        (↔ Search::insertNode, src/search.h:636-722).  ``cand_l`` is the
        candidate distance as NL limb planes [W, S]; everything stays
        2-D."""
        W = tgt.shape[0]
        new_l = gather_planar(new_rows, NL)                       # NL×[W,R]
        node = jnp.concatenate([cand_node, new_rows], axis=1)     # [W,S+R]
        d_l = [jnp.concatenate([cand_l[l], new_l[l] ^ tgt[:, l:l + 1]],
                               axis=1) for l in range(NL)]
        qd = jnp.concatenate([queried, jnp.zeros((W, R), jnp.int32)], axis=1)
        inv = (node < 0).astype(jnp.int32)
        # new entries beyond the valid table (padded fallback rows for
        # empty/absent requests) already arrive as -1 via reply_gather;
        # their distance planes are garbage but masked by inv.
        big = jnp.uint32(0xFFFFFFFF)
        d_l = [jnp.where(inv == 0, dl, big) for dl in d_l]
        # sort by (invalid, dist, node, not-queried) so that among
        # duplicates of a node the already-queried copy comes first
        out = lax.sort(
            (inv,) + tuple(d_l) + (node, 1 - qd),
            dimension=1, num_keys=3 + NL,
        )
        inv_s, node_s = out[0], out[1 + NL]
        qd_s = 1 - out[2 + NL]
        # dedupe: same node appears adjacently (same dist); drop repeats
        dup = jnp.concatenate(
            [jnp.zeros((W, 1), bool),
             (node_s[:, 1:] == node_s[:, :-1]) & (node_s[:, 1:] >= 0)], axis=1)
        inv2 = jnp.where(dup, 1, inv_s)
        out2 = lax.sort(
            (inv2,) + tuple(out[1:1 + NL]) + (node_s, 1 - qd_s),
            dimension=1, num_keys=2 + NL,
        )
        present = out2[0][:, :S] == 0
        node_f = jnp.where(present, out2[1 + NL][:, :S], -1)
        d_f = [jnp.where(present, out2[1 + l][:, :S], big)
               for l in range(NL)]
        qd_f = (1 - out2[2 + NL])[:, :S] * present
        return node_f, d_f, qd_f

    # -- bootstrap: cold start from ONE pseudo-random bootstrap peer per
    # search (like a node boots from a single well-known host) ------------
    empty = n <= 0
    boot = jnp.full((Q, alpha), -1, jnp.int32).at[:, 0].set(
        jnp.where(
            empty, -1,
            (_mix32(q_index.astype(_U32) ^ seed_u)
             % jnp.maximum(n, 1).astype(_U32)).astype(jnp.int32)))
    cand_node = jnp.full((Q, S), -1, jnp.int32)
    cand_l = [jnp.full((Q, S), 0xFFFFFFFF, _U32) for _ in range(NL)]
    queried = jnp.zeros((Q, S), jnp.int32)
    first = reply_gather(targets, pos_t_full, q_index, boot, jnp.int32(0))
    cand_node, cand_l, queried = merge(targets, cand_node, cand_l, queried,
                                       first)

    def synced(cand_node, queried):
        """First min(k, #candidates) candidates all answered
        (↔ isSynced, search.h:734-747).  Replies are instantaneous in this
        network model, so 'queried' doubles as 'replied'; a lossy-network
        model would split the two flags again."""
        present = cand_node[:, :k] >= 0
        return jnp.all(~present | (queried[:, :k] > 0), axis=1) & \
            jnp.any(present, axis=1)

    def make_body(tgt, pt, qidx):
        def body(state):
            cand_node, cand_l, queried, hops, done, round_no = state
            # select the closest α unqueried candidates per active search
            # (↔ searchSendGetValues picking SearchNodes with canGet,
            #  src/dht.cpp:628-639)
            can = (cand_node >= 0) & (queried == 0) & ~done[:, None]
            rank = jnp.cumsum(can.astype(jnp.int32), axis=1)
            sel = can & (rank <= alpha)
            # gather selected rows into [W, alpha] (−1 pad): α static
            # masked max-reductions — a scatter-max here measured slower
            x_rows = jnp.stack(
                [jnp.max(jnp.where(sel & (rank == j + 1), cand_node, -1),
                         axis=1) for j in range(alpha)], axis=1)
            if block_bounds is not None:
                # ROUND FUSION: the selected peers' top distance limb
                # rides the same masked max-reductions (cand_l[0] is
                # x0 ^ t0 — computed by the merge that first admitted
                # the peer), so reply_gather needs NO table access to
                # position the reply blocks and the round's only
                # gather is the fused α·k-row reply fetch.  Bit-exact:
                # a selected lane is unique per rank (cumsum), and
                # unselected slots (x_rows = -1) get d0 = 0 → their
                # replies are masked exactly as the gathered path
                # masked them.
                x_d0 = jnp.stack(
                    [jnp.max(jnp.where(sel & (rank == j + 1), cand_l[0],
                                       _U32(0)), axis=1)
                     for j in range(alpha)], axis=1)
            else:
                x_d0 = None

            new_rows = reply_gather(tgt, pt, qidx, x_rows, round_no + 1,
                                    x_d0)
            queried = jnp.where(sel, 1, queried)
            cand_node, cand_l, queried = merge(
                tgt, cand_node, cand_l, queried, new_rows)

            now_done = synced(cand_node, queried)
            stalled = ~jnp.any((cand_node >= 0) & (queried == 0), axis=1)
            sent = jnp.any(sel, axis=1)
            # a stalling round sends nothing → costs no hop (matches the
            # scalar reference's stall return path)
            hops = jnp.where(~done & sent, hops + 1, hops)
            done = done | now_done | stalled
            return cand_node, cand_l, queried, hops, done, round_no + 1
        return body

    def cond(state):
        done, round_no = state[4], state[5]
        return (~jnp.all(done)) & (round_no < max_hops)

    body_full = make_body(targets, pos_t_full, q_index)
    state = (cand_node, cand_l, queried,
             jnp.zeros((Q,), jnp.int32),
             synced(cand_node, queried) | empty,
             jnp.int32(0))

    if compact_after is None:
        cand_node, cand_l, queried, hops, done, _ = \
            lax.while_loop(cond, body_full, state)
    else:
        cut = min(compact_after, max_hops)

        def cond1(st):
            return (~jnp.all(st[4])) & (st[5] < cut)

        cand_node, cand_l, queried, hops, done, rnd = \
            lax.while_loop(cond1, body_full, state)

        # pack survivors into a cap-wide sub-batch (fill duplicates of
        # row 0 recompute identical values — harmless); run them to
        # convergence at the narrow width, scatter back
        C = compact_cap or max(1, Q // 2)
        sel_rows = jnp.nonzero(~done, size=C, fill_value=0)[0]
        live = jnp.take(~done, sel_rows)

        def sub(a):
            return jnp.take(a, sel_rows, axis=0)

        sub_state = (sub(cand_node), [sub(cl) for cl in cand_l],
                     sub(queried), sub(hops), ~live, rnd)
        body_sub = make_body(sub(targets), sub(pos_t_full), sub(q_index))
        cn2, cl2, qd2, hp2, dn2, rnd2 = \
            lax.while_loop(cond, body_sub, sub_state)

        lv = live[:, None]
        cand_node = cand_node.at[sel_rows].set(
            jnp.where(lv, cn2, sub(cand_node)))
        cand_l = [cl.at[sel_rows].set(jnp.where(lv, c2, sub(cl)))
                  for cl, c2 in zip(cand_l, cl2)]
        queried = queried.at[sel_rows].set(jnp.where(lv, qd2, sub(queried)))
        hops = hops.at[sel_rows].set(jnp.where(live, hp2, sub(hops)))
        done = done.at[sel_rows].set(jnp.where(live, dn2, sub(done)))

        # safety net: if more than C searches survived the cut, finish
        # them at full width (ZERO iterations when the cap held).  The
        # round counter RESTARTS AT THE CUT, not at the sub-loop's end:
        # overflow rows were paused at round `rnd`, so resuming there
        # replays exactly the reply streams the uncompacted engine
        # would have given them (streams key on global query id +
        # round) — bitwise identity holds even on overflow, and the
        # sub-loop cannot starve overflow rows' round budget.  Rows
        # the sub-loop already finished are done and untouched.  (The
        # one residual divergence: a row still unconverged at max_hops
        # after the sub-loop re-enters here and sees its last rounds'
        # streams again — it can only stall/dedup on them, and such
        # rows are reported converged=False either way.)
        cand_node, cand_l, queried, hops, done, _ = lax.while_loop(
            cond, body_full,
            (cand_node, cand_l, queried, hops, done, rnd))

    nodes_k = cand_node[:, :k]
    if NL == N_LIMBS:
        dist = jnp.stack([cl[:, :k] for cl in cand_l], axis=-1)
    else:
        # reconstruct the full 160-bit distances from the final node ids
        # in ONE gather — the merge loop never carried limbs 2-4
        id_l = gather_planar(nodes_k, N_LIMBS)
        dist = jnp.stack(
            [jnp.where(nodes_k >= 0, id_l[l] ^ targets[:, l:l + 1],
                       jnp.uint32(0xFFFFFFFF)) for l in range(N_LIMBS)],
            axis=-1)
    return {
        "nodes": nodes_k,
        "dist": dist,
        "hops": hops,
        "converged": synced(cand_node, queried) & ~empty,
    }


@functools.partial(
    jax.jit,
    static_argnames=("k", "alpha", "search_nodes", "max_hops",
                     "state_limbs", "compact_after", "compact_cap",
                     "block_mode"),
)
def _simulate_lookups_jit(sorted_ids, n_valid, targets, *, seed: int = 0,
                          k: int = TARGET_NODES, alpha: int = ALPHA,
                          search_nodes: int = SEARCH_NODES, max_hops: int = 48,
                          lut=None, state_limbs: int = N_LIMBS,
                          compact_after: "int | None" = None,
                          compact_cap: int = 0, block_mode: str = "lut"):
    """Compiled core of :func:`simulate_lookups` (same contract; the
    public wrapper adds the host-side telemetry envelope).

    Args:
      sorted_ids: uint32 [N, 5], lexicographically sorted network ids
                  (node identity == sorted row index).
      n_valid:    number of real rows in sorted_ids.
      targets:    uint32 [Q, 5] lookup keys.

    Returns dict of:
      nodes     [Q, k] int32  — the k closest nodes found (sorted rows)
      dist      [Q, k, 5]     — their XOR distances
      hops      [Q] int32     — rounds until the first-k set had replied
      converged [Q] bool

    Single-device instantiation of :func:`_lookup_engine`.  The
    table-sharded multi-chip form (table rows partitioned over a mesh
    axis, exceeding one chip's HBM) is
    ``parallel.tp_simulate_lookups`` — same engine, same results.
    ``state_limbs=2`` ranks merge candidates by the top 64 distance
    bits only (5-operand merge sorts instead of 8 — see
    :func:`_lookup_engine`); bitwise identical to the default absent
    64-bit distance ties.

    ``block_mode`` selects how the simulated reply model computes each
    peer's prefix-block edges: ``"lut"`` (default) = two LUT reads per
    edge (:func:`_lut_block_bounds`) — exact for prefixes up to the LUT
    width, clamped to the containing bucket beyond it; ``"exact"`` =
    the per-round batched binary search (the pre-round-5 model, exact
    at any depth, measured 85% of the round's wall-clock at 10M —
    benchmarks/exp_round_r5.py).  On uniform tables at
    ``default_lut_bits`` the two are statistically indistinguishable
    (a clamped bucket with ≥ k rows exists for ~4 of 16.7M buckets at
    N=10M and affects a reply only when a target lands in it past the
    LUT depth); on heavily CLUSTERED tables the clamp widens deep
    blocks, so hop-trajectory studies of adversarial id distributions
    should pass ``block_mode="exact"`` (cf. the positioning guard
    ``_guarded_lower_bound``, which handles clustering for the
    positioning search automatically).
    """
    if block_mode not in ("lut", "exact"):
        raise ValueError(f"block_mode must be 'lut' or 'exact', "
                         f"got {block_mode!r}")
    N = sorted_ids.shape[0]
    Q = targets.shape[0]
    n = jnp.asarray(n_valid, jnp.int32)
    seed_u = jnp.asarray(seed, dtype=jnp.int32).astype(_U32)

    # Layout note (measured on v5e): any [.., .., 5] intermediate pads
    # its 5-lane minor dim to 128 in TPU tiled layout (25× physical
    # traffic — ~2.7 GB per materialized [Q, S+R, 5] at Q=131072), and
    # per-element row gathers run issue-bound at ~190K rows/ms.  So the
    # loop state keeps distances as 5 separate [Q, S] limb planes, id
    # gathers go through the transposed [5, N] table (planar output,
    # no lane padding), and the positioning searches use the prefix LUT
    # behind a device-side soundness guard (_guarded_lower_bound):
    # clustered tables whose largest bucket exceeds the bounded
    # in-bucket budget take the full-depth search instead.
    sorted_t = sorted_ids.T                            # [5, N] one transpose
    if lut is None:
        # callers with a stable table should build this once with
        # build_prefix_lut and pass it in — rebuilt here it costs a
        # device searchsorted over N keys on every invocation
        lut = build_prefix_lut(sorted_ids, n, bits=default_lut_bits(N))
    # sound positioning: LUT fast path only when every bucket fits the
    # bounded in-bucket budget, else full-depth search (lax.cond)
    lower = _guarded_lower_bound(sorted_ids, n, lut)

    def gather_planar(rows, limbs=N_LIMBS):
        """rows [...] int32 → list of `limbs` limb arrays shaped like
        rows (top limbs first — all the merge ranking needs).  ONE
        fused take per call — ops.sorted_table.fused_gather_planar is
        the shared primitive (pinned against the xor_topk.gather_rows
        oracle)."""
        return fused_gather_planar(sorted_t, rows, limbs)

    return _lookup_engine(gather_planar, lower, n, targets,
                          jnp.arange(Q, dtype=jnp.int32), Q, seed_u,
                          k=k, alpha=alpha, search_nodes=search_nodes,
                          max_hops=max_hops, state_limbs=state_limbs,
                          compact_after=compact_after,
                          compact_cap=compact_cap,
                          block_bounds=(
                              (lambda t0, L: _lut_block_bounds(lut, t0, L))
                              if block_mode == "lut" else None))


def _is_tracer(x) -> bool:
    try:
        return isinstance(x, jax.core.Tracer)
    except AttributeError:          # jax moved core — fail open (no
        return False                # instrumentation, never a crash)


_TRACE_MAX_ROUND_SPANS = 64


def record_wave(out, elapsed_s: float, wave_width: int, *,
                mode: str = "single", mesh_t: int = 1) -> None:
    """Feed one completed search wave into the telemetry spine
    (ISSUE-3): ``dht_search_wave_seconds`` (the OPEN ≤8 ms 1024-wave
    p50 bound is exactly this histogram's p50 at width 1024, PARITY.md),
    per-round latency (wave wall / deepest round — rounds advance in
    lockstep inside the compiled while_loop, so the per-round figure is
    the wave quotient, not a per-round host probe), and the wave-width /
    hops distributions.  Shared by the single-device engine and the
    tp-sharded twin (``mode="tp"``, parallel/sharded.py).

    ISSUE-4: when an ambient trace context is active the same envelope
    records the wave into the distributed tracer — one
    ``dht.search.wave`` child span plus one ``dht.search.round`` child
    per round.  Context-gated ON PURPOSE: an untraced bench loop would
    otherwise mint ~rounds+1 root spans per wave into the shared ring
    and evict the flight-recorder events it exists to retain (found by
    review) — to trace a wave, activate a root first (``with
    tracing.activate(TraceContext.new_root()): simulate_lookups(...)``,
    the exact recipe PARITY gives for settling the OPEN p95-wave bound
    on chip).  Round spans carry the wave-quotient duration — the
    rounds run in lockstep inside the compiled while_loop, so the even
    split IS the attribution the telemetry histogram reports.
    Host-side only: the traced computation ran BEFORE this call —
    tracing cannot perturb the kernels (pinned in
    tests/test_tracing.py)."""
    from .. import telemetry, tracing
    reg = telemetry.get_registry()
    reg.histogram("dht_search_wave_seconds", mode=mode).observe(elapsed_s)
    reg.histogram("dht_search_wave_width", mode=mode).observe(wave_width)
    hops = np.asarray(out["hops"])
    reg.histogram("dht_search_hops", mode=mode).observe_many(hops)
    rounds = int(hops.max()) if hops.size else 0
    if rounds > 0:
        reg.histogram("dht_search_round_seconds", mode=mode).observe(
            elapsed_s / rounds)
    tr = tracing.get_tracer()
    ctx = tracing.current()
    # ISSUE-15: the search wave IS the device stage of every op it
    # carries — feed the waterfall the same timed span, split
    # compile-vs-execute per launch shape (mode × width) so the bench
    # loops measure the profiler at its real per-wave hook cost
    from .. import waterfall
    wf = waterfall.get_profiler()
    if wf.enabled:
        key = ("search", mode, int(wave_width))
        stage = ("device_compile" if wf.first_launch(key)
                 else "device_wait")
        wf.observe(stage, elapsed_s,
                   exemplar=tracing.current_trace_hex())
    if tr.enabled and ctx is not None:
        end = time.time()
        start = end - elapsed_s
        # ISSUE-6: device-cost attribution from the kernel ledger — the
        # scaled cost-model estimate (bytes/flops) and the achieved HBM
        # fraction ride the wave span, so a Perfetto load shows which
        # waves ran memory-bound and how far from peak.  Empty dict (one
        # cached-flag check) until someone computes the ledger; cost
        # quantified by captures/ledger_overhead.json.
        from .. import profiling
        cost = profiling.wave_attrs(int(wave_width), rounds, elapsed_s,
                                    mode=mode, mesh_t=mesh_t)
        wave_ctx = tr.record("dht.search.wave", start, elapsed_s,
                             parent=ctx, mode=mode,
                             width=int(wave_width), rounds=rounds, **cost)
        if wave_ctx is not None and 0 < rounds <= _TRACE_MAX_ROUND_SPANS:
            per_round = elapsed_s / rounds
            for i in range(rounds):
                tr.record("dht.search.round", start + i * per_round,
                          per_round, parent=wave_ctx, mode=mode, round=i)


def simulate_lookups(sorted_ids, n_valid, targets, **kw):
    """Run Q iterative lookups to convergence — the public entry point;
    see :func:`_simulate_lookups_jit` for the full argument contract.

    Telemetry envelope over the compiled engine: times the wave with
    a host-side span (``perf_counter`` around ``block_until_ready``,
    plus the matching ``jax.profiler.TraceAnnotation``) and records the
    wave/hops histograms.  Host-side ONLY — the traced computation is
    byte-for-byte :func:`_simulate_lookups_jit`, so results are
    bit-identical with telemetry on or off (pinned in
    tests/test_telemetry.py).  Under an outer trace (e.g. the bench
    drivers jit a body that calls this) or with the registry disabled,
    the envelope vanishes and the call degrades to the bare jit —
    no blocking, no transfers."""
    from .. import telemetry
    reg = telemetry.get_registry()
    if not reg.enabled or _is_tracer(targets) or _is_tracer(sorted_ids):
        return _simulate_lookups_jit(sorted_ids, n_valid, targets, **kw)
    with reg.span("dht_search_wave_seconds", record=False) as sp:
        out = _simulate_lookups_jit(sorted_ids, n_valid, targets, **kw)
        jax.block_until_ready(out)
    record_wave(out, sp.elapsed, targets.shape[0], mode="single")
    return out


# ---------------------------------------------------------------------------
# Scalar reference implementation (oracle for hop-count parity and the CPU
# baseline) — same network model, sequential python, one lookup at a time,
# mirroring the shape of the reference's searchStep loop.
# ---------------------------------------------------------------------------

def scalar_lookup(sorted_ids_np: np.ndarray, n: int, target_np: np.ndarray,
                  *, seed: int = 0, k: int = TARGET_NODES, alpha: int = ALPHA,
                  search_nodes: int = SEARCH_NODES, max_hops: int = 48,
                  rng=None):
    """Sequential lookup with the same candidate-set/α/convergence
    semantics and the same network reply model as simulate_lookups (reply
    sampling is random rather than counter-hashed, so parity is
    statistical, not bitwise).  Returns (nodes, hops, converged)."""
    if rng is None:
        rng = np.random.default_rng(seed)

    def row_int(i):
        return int.from_bytes(ids_to_bytes(sorted_ids_np[i]).tobytes(), "big")

    t_int = int.from_bytes(ids_to_bytes(target_np).tobytes(), "big")

    def lower_bound(v: int) -> int:
        lo, hi = 0, n
        while lo < hi:
            mid = (lo + hi) // 2
            if row_int(mid) < v:
                lo = mid + 1
            else:
                hi = mid
        return lo

    pos_t = lower_bound(t_int)

    def reply(x_row: int) -> list:
        x_int = row_int(x_row)
        cb = 160 - (x_int ^ t_int).bit_length() if x_int != t_int else 160
        plen = min(cb + 1, 160)
        mask = ((1 << plen) - 1) << (160 - plen) if plen else 0
        p_lo = t_int & mask
        p_hi = p_lo | ((1 << (160 - plen)) - 1)
        lo = lower_bound(p_lo)
        ub = lower_bound(p_hi + 1)
        size = ub - lo
        if size >= k:
            return [lo + int(v) for v in rng.integers(0, size, k)]
        R = alpha * k
        base = min(max(pos_t - R // 2, 0), max(n - R, 0))
        j = int(rng.integers(0, alpha))          # this peer's window slice
        return [min(base + j * k + jj, n - 1) for jj in range(k)]

    # candidate set: list of (dist, row, queried, replied)
    cands: dict[int, list] = {}

    def insert(row):
        if row in cands:
            return
        cands[row] = [row_int(row) ^ t_int, row, False, False]

    boot = int(rng.integers(0, n))
    for r in reply(boot):
        insert(r)

    hops = 0
    while hops < max_hops:
        ordered = sorted(cands.values())[:search_nodes]
        cands = {c[1]: c for c in ordered}
        topk = ordered[:k]
        if topk and all(c[3] for c in topk):
            return [c[1] for c in topk], hops, True
        to_query = [c for c in ordered if not c[2]][:alpha]
        if not to_query:
            return [c[1] for c in topk], hops, False
        hops += 1
        for c in to_query:
            c[2] = c[3] = True
            for r in reply(c[1]):
                insert(r)
    ordered = sorted(cands.values())[:k]
    return [c[1] for c in ordered], hops, False
