"""Listen-operation dedup caches (reference src/op_cache.{h,cpp}).

Identical/overlapping ``listen`` calls share one network subscription:

- :class:`OpValueCache` — ref-counts values across the underlying
  subscriptions feeding it (a value announced by several network ops
  expires only when all of them expire it).
- :class:`OpCache` — one network op + its local listeners; lingers 60 s
  after the last listener leaves so a quick re-listen reuses it.
- :class:`SearchCache` — maps Query → OpCache per search, routing a new
  listen to an existing op whose query satisfies it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..utils import TIME_MAX
from .listener import LocalListener, ValueCallback
from .value import Filter, Filters, Query, Value

OP_LINGER = 60.0                 # op_cache.h:120: EXPIRATION


@dataclass
class _RefSlot:
    data: Value
    ref_count: int = 1


class OpValueCache:
    """(op_cache.h:26-67, op_cache.cpp:25-80)"""

    def __init__(self, callback: ValueCallback):
        self._values: Dict[int, _RefSlot] = {}
        self._callback = callback

    @staticmethod
    def cache_callback(cb: ValueCallback) -> ValueCallback:
        """Wrap a user callback so repeated adds/partial expires collapse
        (used around Dht.listen user cbs, dht.cpp:836)."""
        cache = OpValueCache(cb)
        return cache.on_value

    def on_value(self, vals: List[Value], expired: bool) -> bool:
        return self.on_values_expired(vals) if expired else self.on_values_added(vals)

    def on_values_added(self, vals: List[Value]) -> bool:
        new_values = []
        for v in vals:
            slot = self._values.get(v.id)
            if slot is None:
                self._values[v.id] = _RefSlot(v)
                new_values.append(v)
            else:
                slot.ref_count += 1
        if not new_values:
            return True
        # only an explicit False unsubscribes (None stays subscribed,
        # matching LocalListener.notify)
        return self._callback(new_values, False) is not False

    def on_values_expired(self, vals: List[Value]) -> bool:
        gone = []
        for v in vals:
            slot = self._values.get(v.id)
            if slot is not None:
                slot.ref_count -= 1
                if slot.ref_count == 0:
                    gone.append(slot.data)
                    del self._values[v.id]
        if not gone:
            return True
        return self._callback(gone, True) is not False

    def get(self, f: Optional[Filter] = None) -> List[Value]:
        return Filters.apply(f, (s.data for s in self._values.values()))

    def get_by_id(self, vid: int) -> Optional[Value]:
        slot = self._values.get(vid)
        return slot.data if slot else None

    def get_values(self) -> List[Value]:
        return [s.data for s in self._values.values()]


class OpCache:
    """One shared network listen + its local listeners
    (op_cache.h:70-127)."""

    def __init__(self, now: float = 0.0, clock=None):
        self.cache = OpValueCache(self._dispatch)
        self._listeners: Dict[int, LocalListener] = {}
        self._last_removed = now
        self._clock = clock
        self.search_token = 0       # token of the underlying network op

    def on_value(self, vals: List[Value], expired: bool) -> bool:
        """Feed from the network op.  Always True: the shared op must
        survive the 60 s listener-less linger so a quick re-listen reuses
        a live subscription — teardown happens only through
        SearchCache.expire/cancel_all cancelling ``search_token``."""
        self.cache.on_value(vals, expired)
        return True

    def _dispatch(self, vals: List[Value], expired: bool) -> bool:
        # A callback returning False unsubscribes (the ValueCallback
        # contract, listener.py); notify() also skips listeners whose
        # filter leaves nothing.
        for token, l in list(self._listeners.items()):
            if not l.notify(vals, expired):
                self._listeners.pop(token, None)
                if self._clock is not None:
                    self._last_removed = self._clock()
        return True

    def add_listener(self, token: int, cb: ValueCallback, query: Optional[Query],
                     f: Optional[Filter], now: float = 0.0) -> None:
        """Register + replay current cache state (op_cache.h:87-90).
        Replay goes through notify(): nothing fires when the cache holds
        nothing the filter passes, and an explicit False return
        unsubscribes immediately (one-shot listener satisfied from
        cache)."""
        l = LocalListener(query, f, cb)
        self._listeners[token] = l
        if not l.notify(self.cache.get(), False):
            self._listeners.pop(token, None)
            self._last_removed = now

    def remove_listener(self, token: int, now: float) -> bool:
        self._last_removed = now
        return self._listeners.pop(token, None) is not None

    def remove_all(self) -> None:
        self._listeners.clear()

    def is_done(self) -> bool:
        return not self._listeners

    def get_expiration(self) -> float:
        return TIME_MAX if self._listeners else self._last_removed + OP_LINGER

    def is_expired(self, now: float) -> bool:
        # inclusive boundary, matching SearchCache.expire: an op whose
        # linger ends exactly now IS expired (a strict '<' here would
        # re-inherit the exp == now virtual-clock live-lock)
        return not self._listeners and self.get_expiration() <= now

    def get(self, f: Optional[Filter] = None) -> List[Value]:
        return self.cache.get(f)


class SearchCache:
    """Query-keyed registry of shared listen ops (op_cache.h:129-153).
    ``clock`` (e.g. ``scheduler.time``) timestamps listener removals that
    happen inside value dispatch, so the linger window is measured from
    the true last removal."""

    def __init__(self, clock=None):
        self._ops: Dict[Query, OpCache] = {}
        self._clock = clock
        self._next_token = 1
        self._next_expiration = TIME_MAX

    def listen(self, get_cb: ValueCallback, query: Query, f: Optional[Filter],
               on_listen: Callable[[Query, ValueCallback], int],
               now: float = 0.0) -> int:
        """Attach a listener, creating the network op only if no
        existing op's query satisfies this one (op_cache.cpp:166-193).
        ``on_listen(query, cb)`` starts the network op and returns its
        token."""
        op = self._ops.get(query)
        if op is None:
            for q, cand in self._ops.items():
                if query.is_satisfied_by(q):
                    op = cand
                    break
        if op is None:
            op = OpCache(now, clock=self._clock)
            self._ops[query] = op
            op.search_token = on_listen(query, op.on_value)
        token = self._next_token
        self._next_token += 1
        if self._next_token == 0:
            self._next_token = 1
        op.add_listener(token, get_cb, query, f, now)
        return token

    def cancel_listen(self, token: int, now: float) -> bool:
        for op in self._ops.values():
            if op.remove_listener(token, now):
                self._next_expiration = min(self._next_expiration,
                                            op.get_expiration())
                return True
        return False

    def cancel_all(self, on_cancel: Callable[[int], None]) -> None:
        for op in self._ops.values():
            op.remove_all()
            on_cancel(op.search_token)
        self._ops.clear()

    def expire(self, now: float, on_cancel: Callable[[int], None]) -> float:
        """Drop ops whose linger has elapsed; returns next expiration
        (op_cache.cpp:161-178).

        Boundary is INCLUSIVE (``exp <= now``), unlike the reference's
        strict ``<``: the expire job re-arms itself at the returned
        time, and a surviving op with ``exp == now`` would re-arm the
        job at the CURRENT instant — a live-lock under a virtual clock
        that only advances between events (observed: a search's
        expire_ops job spinning at one timestamp until the test
        harness's event budget drained).  Real monotonic clocks advance
        between scheduler runs, which is the only reason the strict
        form terminates in the reference."""
        self._next_expiration = TIME_MAX
        for q in list(self._ops):
            op = self._ops[q]
            exp = op.get_expiration()
            if exp <= now:
                del self._ops[q]
                on_cancel(op.search_token)
            else:
                self._next_expiration = min(self._next_expiration, exp)
        return self._next_expiration

    def get_expiration(self) -> float:
        return self._next_expiration

    def get(self, f: Optional[Filter] = None) -> List[Value]:
        if len(self._ops) == 1:
            return next(iter(self._ops.values())).get(f)
        seen: Dict[int, Value] = {}
        for op in self._ops.values():
            for v in op.get(f):
                seen.setdefault(v.id, v)
        return list(seen.values())

    def get_by_id(self, vid: int) -> Optional[Value]:
        for op in self._ops.values():
            v = op.cache.get_by_id(vid)
            if v is not None:
                return v
        return None

    def __len__(self) -> int:
        return len(self._ops)
