"""Unified telemetry: one metrics spine from kernel rounds to the proxy.

The repo's perf story used to live in three disconnected islands —
``net/engine.py MessageStats``, ``proxy/server.py ServerStats`` and
``runtime/dht.py get_nodes_stats`` — plus one-off ``benchmarks/exp_*``
drivers for anything kernel-side.  This module is the shared spine they
all feed (↔ the reference exposing ``Dht::getNodesStats`` and the proxy
``STATS /`` route as a product surface, dht_proxy_server.cpp:206-232):

- :class:`MetricsRegistry` — zero-dependency counters, gauges and
  log-bucketed histograms, labeled by name + sorted ``(key, value)``
  tuples.  One process-global default instance (:func:`get_registry`)
  aggregates every component; a multi-node test process sums its nodes
  into the same series (documented, deliberate — per-node cardinality
  is the embedder's concern, label if you need the split).
- :meth:`MetricsRegistry.span` — a host-side ``perf_counter`` timer
  that also enters a ``jax.profiler.TraceAnnotation`` of the SAME name,
  so device traces (``jax.profiler.trace``) align with the host spans
  that wrap ``block_until_ready``.  Instrumentation stays off the
  kernel trace: spans time *around* compiled calls, never inside them,
  so kernels remain bit-identical with telemetry enabled.
- Export: :meth:`snapshot` (JSON-able dict — ``DhtRunner.get_metrics``),
  :meth:`prometheus` (text exposition v0.0.4 — the proxy ``GET /stats``
  route), and the ``stats`` REPL command in tools/dhtnode.py.

Everything is cheap enough to leave on by default (one dict lookup +
a few float ops per event; hot callers cache the metric handles).  Flip
``get_registry().enabled = False`` to skip span timing/blocking in
latency-critical embeddings; recorded kernels and results are identical
either way (captures/telemetry_overhead.json quantifies the on-cost).

Import-light by design: stdlib only at module import (the jax profiler
is looked up lazily inside :meth:`span`), so the scheduler/net layers
keep working in minimal containers without the jax wheel.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "Span",
    "get_registry", "quantile_from_buckets", "snapshot_diff",
]

# histogram buckets are powers of two: bucket i covers
# (2^(i-1-_H_OFFSET), 2^(i-_H_OFFSET)]; index 0 is the catch-all for
# v <= 2^-_H_OFFSET (~1 ns for seconds-valued series), the last for
# anything above 2^(_H_SPAN-_H_OFFSET).  One scheme for every series —
# seconds, wave widths, hop counts — keeps quantile math and the
# exposition identical everywhere.
_H_OFFSET = 30
_H_SPAN = 94                  # up to 2^64


def _bucket_index(v: float) -> int:
    if not v > 0.0:
        return 0
    e = math.frexp(v)[1]      # v in (2^(e-1), 2^e]  (frexp: m in [0.5, 1))
    if math.ldexp(1.0, e - 1) == v:
        e -= 1                # exact power of two sits in the lower bucket
    return min(max(e + _H_OFFSET, 0), _H_SPAN - 1)


def _bucket_le(i: int) -> float:
    return math.ldexp(1.0, i - _H_OFFSET)


def quantile_from_buckets(items, total: int, q: float) -> float:
    """Linear-interpolated quantile over sorted ``(bucket_index,
    count)`` pairs of the log-bucket scheme — the ONE copy of the
    interpolation used by :meth:`Histogram.quantile` and the windowed
    bucket-delta readers in opendht_tpu/health.py (keeping the two
    from diverging).  0.0 when ``total`` is zero."""
    if total <= 0:
        return 0.0
    target = q * total
    cum = 0
    for i, c in items:
        if cum + c >= target:
            lo = 0.0 if i == 0 else _bucket_le(i - 1)
            hi = _bucket_le(i)
            frac = (target - cum) / c if c else 1.0
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
        cum += c
    return _bucket_le(items[-1][0])


class Counter:
    """Monotonic event count."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """Point-in-time value (queue depths, table health)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def dec(self, n: float = 1.0) -> None:
        self.value -= n


class Histogram:
    """Log-bucketed (base-2) distribution: count, sum, sparse buckets.

    The power-of-two scheme gives ~±50% bucket resolution over the full
    dynamic range from nanoseconds to hours with at most ``_H_SPAN``
    buckets and no per-metric configuration — quantiles interpolate
    linearly inside the landing bucket, which is accurate enough for
    p50/p95 alerting (testing/network_monitor.py) and far cheaper than
    exact reservoirs on the per-packet hot paths."""

    __slots__ = ("count", "sum", "buckets", "exemplars", "_lock")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.buckets: Dict[int, int] = {}
        # per-bucket latest exemplar (round 19): bucket -> (value,
        # trace id) — a hot bucket links to a reconstructable trace
        # through the round-9 assembler.  JSON/snapshot side only; the
        # prometheus() v0.0.4 text has no exemplar syntax and stays
        # byte-compatible with pre-exemplar scrapers.
        self.exemplars: Dict[int, tuple] = {}
        self._lock = threading.Lock()

    def observe(self, v: float, exemplar: Optional[str] = None) -> None:
        v = float(v)
        i = _bucket_index(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.buckets[i] = self.buckets.get(i, 0) + 1
            if exemplar is not None:
                self.exemplars[i] = (v, exemplar)

    def observe_many(self, values: Iterable[float]) -> None:
        """Bulk insert (one lock, numpy-bucketed when available) — used
        for per-query series like hop counts at wave widths of 10^5+."""
        try:
            import numpy as np
            v = np.asarray(list(values) if not hasattr(values, "__len__")
                           else values, dtype=np.float64).ravel()
        except Exception:
            for x in values:
                self.observe(x)
            return
        if v.size == 0:
            return
        pos = v > 0.0
        e = np.zeros(v.shape, dtype=np.int64)
        if pos.any():
            ex = np.frexp(v[pos])[1].astype(np.int64)
            # exact powers of two belong to the lower bucket
            ex -= (np.ldexp(1.0, ex - 1) == v[pos])
            e[pos] = ex
        idx = np.where(pos, np.clip(e + _H_OFFSET, 0, _H_SPAN - 1), 0)
        counts = np.bincount(idx, minlength=_H_SPAN)
        nz = np.nonzero(counts)[0]
        with self._lock:
            self.count += int(v.size)
            self.sum += float(v.sum())
            for i in nz:
                i = int(i)
                self.buckets[i] = self.buckets.get(i, 0) + int(counts[i])

    def quantile(self, q: float) -> float:
        """Approximate quantile by linear interpolation inside the
        landing bucket; 0.0 when empty."""
        with self._lock:
            total = self.count
            items = sorted(self.buckets.items())
        return quantile_from_buckets(items, total, q)

    def raw(self) -> tuple:
        """Consistent ``(count, sum, {bucket_index: count})`` snapshot —
        the windowed readers (opendht_tpu/health.py) diff two of these
        to get a bucket-exact view of one time window without any new
        instrumentation on the observing side."""
        with self._lock:
            return self.count, self.sum, dict(self.buckets)

    def to_dict(self) -> dict:
        with self._lock:
            items = sorted(self.buckets.items())
            count, total = self.count, self.sum
            ex = sorted(self.exemplars.items())
        out = {
            "count": count,
            "sum": total,
            "buckets": [[_bucket_le(i), c] for i, c in items],
        }
        if ex:
            # [upper bound, exemplar value, trace id] — absent (not
            # empty) when no exemplar was ever stamped, so existing
            # dict-shape consumers see no new key until they opt in
            out["exemplars"] = [[_bucket_le(i), v, t] for i, (v, t) in ex]
        return out


class Span:
    """Result handle of :meth:`MetricsRegistry.span`: ``elapsed`` holds
    the wall seconds once the ``with`` block exits."""

    __slots__ = ("elapsed",)

    def __init__(self):
        self.elapsed = 0.0


class _SpanCtx:
    __slots__ = ("_hist", "_name", "_ann", "_t0", "_span")

    def __init__(self, hist: Optional[Histogram], name: str):
        self._hist = hist
        self._name = name
        self._ann = None
        self._span = Span()

    def __enter__(self) -> Span:
        ann_cls = _trace_annotation()
        if ann_cls is not None:
            try:
                self._ann = ann_cls(self._name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        self._t0 = time.perf_counter()
        return self._span

    def __exit__(self, *exc) -> None:
        dt = time.perf_counter() - self._t0
        if self._ann is not None:
            try:
                self._ann.__exit__(*exc)
            except Exception:
                pass
        self._span.elapsed = dt
        if self._hist is not None:
            self._hist.observe(dt)


_TRACE_ANNOTATION: "list | None" = None


def _trace_annotation():
    """jax.profiler.TraceAnnotation, resolved once, None without jax."""
    global _TRACE_ANNOTATION
    if _TRACE_ANNOTATION is None:
        try:
            from jax.profiler import TraceAnnotation
            _TRACE_ANNOTATION = [TraceAnnotation]
        except Exception:
            _TRACE_ANNOTATION = [None]
    return _TRACE_ANNOTATION[0]


def _label_key(labels: dict) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _escape(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt(v: float) -> str:
    if v == float("inf"):
        return "+Inf"
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(v) if isinstance(v, float) else str(v)


def _series_name(name: str, labels: Tuple[Tuple[str, str], ...],
                 extra: Optional[Tuple[str, str]] = None) -> str:
    pairs = list(labels) + ([extra] if extra else [])
    if not pairs:
        return name
    inner = ",".join('%s="%s"' % (k, _escape(v)) for k, v in pairs)
    return "%s{%s}" % (name, inner)


class MetricsRegistry:
    """Get-or-create metric store with JSON + Prometheus export."""

    _KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}

    def __init__(self):
        self._lock = threading.Lock()
        # name -> (kind, {label_key -> metric})
        self._metrics: Dict[str, Tuple[str, dict]] = {}
        #: master switch for span *timing* (metrics stay writable; hot
        #: paths may consult it to skip blocking instrumentation)
        self.enabled = True

    # ------------------------------------------------------------- factories
    def _get(self, kind: str, name: str, labels: dict):
        key = _label_key(labels)
        with self._lock:
            ent = self._metrics.get(name)
            if ent is None:
                ent = (kind, {})
                self._metrics[name] = ent
            elif ent[0] != kind:
                raise ValueError(
                    "metric %r already registered as %s, requested %s"
                    % (name, ent[0], kind))
            m = ent[1].get(key)
            if m is None:
                m = ent[1][key] = self._KINDS[kind]()
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", name, labels)

    def histogram(self, name: str, **labels) -> Histogram:
        return self._get("histogram", name, labels)

    def span(self, name: str, record: bool = True, **labels) -> _SpanCtx:
        """``with reg.span("dht_x_seconds") as s: ...`` — times the block
        with ``perf_counter`` (callers put ``block_until_ready`` inside),
        observes into histogram ``name`` and emits a matching
        ``jax.profiler.TraceAnnotation`` so device traces line up with
        the host span.  ``s.elapsed`` is readable after exit.  With the
        registry disabled — or ``record=False``, for callers that feed
        the elapsed time into their own series — the histogram write is
        skipped but the annotation still fires (profiles stay labeled)."""
        hist = (self.histogram(name, **labels)
                if self.enabled and record else None)
        return _SpanCtx(hist, name)

    def families(self) -> Dict[str, str]:
        """``{family_name: kind}`` of every registered metric family —
        the non-mutating enumeration the round-17 history recorder
        walks each tick (``snapshot()`` would compute quantiles for
        every histogram in the process; the recorder only needs names
        to feed :meth:`series`)."""
        with self._lock:
            return {n: kind for n, (kind, _d) in self._metrics.items()}

    def series(self, name: str) -> dict:
        """All label series of one metric family as ``{label_key:
        metric}`` (empty when the family was never written).  Lets a
        reader aggregate over labels — e.g. the health evaluator's
        timeout ratio sums every ``type=`` series — without the full
        :meth:`snapshot` (which computes quantiles for every
        histogram in the process)."""
        with self._lock:
            ent = self._metrics.get(name)
            return dict(ent[1]) if ent is not None else {}

    # --------------------------------------------------------------- export
    def snapshot(self) -> dict:
        """JSON-able dump: ``{"counters": {...}, "gauges": {...},
        "histograms": {series: {count, sum, p50, p95, p99, buckets}}}``.
        Series keys use the Prometheus form ``name{k="v"}``."""
        with self._lock:
            metrics = {n: (k, dict(d)) for n, (k, d) in self._metrics.items()}
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for name in sorted(metrics):
            kind, series = metrics[name]
            for key in sorted(series):
                m = series[key]
                sname = _series_name(name, key)
                if kind == "counter":
                    out["counters"][sname] = m.value
                elif kind == "gauge":
                    out["gauges"][sname] = m.value
                else:
                    d = m.to_dict()
                    d["p50"] = m.quantile(0.50)
                    d["p95"] = m.quantile(0.95)
                    d["p99"] = m.quantile(0.99)
                    out["histograms"][sname] = d
        return out

    def prometheus(self) -> str:
        """Text exposition format v0.0.4 (one ``# TYPE`` line per
        family; histograms as cumulative ``_bucket``/``_sum``/``_count``
        with the standard ``le`` label)."""
        with self._lock:
            metrics = {n: (k, dict(d)) for n, (k, d) in self._metrics.items()}
        lines: List[str] = []
        for name in sorted(metrics):
            kind, series = metrics[name]
            lines.append("# TYPE %s %s" % (name, kind))
            for key in sorted(series):
                m = series[key]
                if kind == "histogram":
                    d = m.to_dict()
                    cum = 0
                    for le, c in d["buckets"]:
                        cum += c
                        lines.append("%s %d" % (_series_name(
                            name + "_bucket", key, ("le", _fmt(le))), cum))
                    lines.append("%s %d" % (_series_name(
                        name + "_bucket", key, ("le", "+Inf")), d["count"]))
                    lines.append("%s %s" % (
                        _series_name(name + "_sum", key), _fmt(d["sum"])))
                    lines.append("%s %d" % (
                        _series_name(name + "_count", key), d["count"]))
                else:
                    lines.append("%s %s" % (
                        _series_name(name, key), _fmt(float(m.value))))
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Zero every metric IN PLACE (tests; not part of the serving
        surface).  Identity-preserving: hot paths cache metric handles
        (engine/scheduler per-instance, request.py/table.py module
        caches), so clearing the dict would orphan those writers —
        instead each existing object is zeroed and keeps reporting."""
        with self._lock:
            for _kind, series in self._metrics.values():
                for m in series.values():
                    if isinstance(m, Histogram):
                        with m._lock:
                            m.count = 0
                            m.sum = 0.0
                            m.buckets.clear()
                            m.exemplars.clear()
                    else:
                        m.value = 0


def snapshot_diff(before: dict, after: dict) -> dict:
    """Per-series delta of two :meth:`MetricsRegistry.snapshot` dicts
    (ISSUE-4 satellite: the overhead drivers and the tracing/telemetry
    tests all need "what advanced between these two points" — this
    replaces the hand-rolled registry subtraction).

    Returns the same ``{"counters", "gauges", "histograms"}`` shape:
    counters/gauges as value deltas (zero-delta series dropped),
    histograms as ``{"count": Δcount, "sum": Δsum}`` for series whose
    count moved.  Series present only in ``after`` diff against zero."""
    out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
    for kind in ("counters", "gauges"):
        b = before.get(kind, {})
        a = after.get(kind, {})
        for key in sorted(set(a) | set(b)):
            d = a.get(key, 0) - b.get(key, 0)
            if d:
                out[kind][key] = d
    bh = before.get("histograms", {})
    ah = after.get("histograms", {})
    for key in sorted(set(ah) | set(bh)):
        ad = ah.get(key, {})
        bd = bh.get(key, {})
        dc = ad.get("count", 0) - bd.get("count", 0)
        if dc:
            out["histograms"][key] = {
                "count": dc,
                "sum": ad.get("sum", 0.0) - bd.get("sum", 0.0),
            }
    return out


_global_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-global registry every layer feeds by default."""
    return _global_registry
