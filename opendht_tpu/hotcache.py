"""Hot-key serving cache: the acting half of the observe→act loop.

Round 15 *detects* heavy hitters (the keyspace observatory's device
count-min sketch, ``hot_key_emerged`` events, per-shard loads); nothing
*consumed* them — a Zipf(1.1) single-key flood still paid a full
iterative-search launch per hot get, and the closest-8 storing nodes
stayed the bottleneck.  This module closes the loop (ISSUE-11
tentpole), the way the reference's own design says to: Kademlia caches
along the lookup path and widens popular keys' replica sets (Maymounkov
& Mazières 2002 §4.1), and Fan et al. (*Small Cache, Big Effect*, SoCC
2011) prove a front-end cache of only the O(n log n) hottest items
load-balances an arbitrarily skewed workload — exactly the top-K the
observatory already computes.

Three coupled pieces:

- :class:`HotValueCache` — a bounded table of canonical 20-byte ids
  (device-resident, uint32 ``[C, 5]`` limbs — the operand of the
  ``ops/cache_probe.py`` XOR-compare kernel) + host-side value
  payloads.  Keyed off :meth:`KeyspaceObservatory.top_keys`: the cache
  SUBSCRIBES to the observatory tick — keys crossing the hot rule are
  admitted (values pulled from the local store, or filled from a
  completed get via :meth:`offer`), keys decaying out of the hot set
  are evicted, expired entries swept, and an observed put to a cached
  key invalidates it (:meth:`invalidate` — freshness: a put must be
  visible on the next get, never a stale hit).
- **Serve-from-cache fast path** — ``runtime/wave_builder.py`` runs
  :meth:`probe_wave` (one batched XOR-compare launch over the wave's
  ``[Q]`` targets) BEFORE ``_launch``: hits are served from the host
  payloads and never join the lookup launch at all; the miss set falls
  through to the unchanged wave.  Only pure-get refills are eligible —
  an announce/listen/query refill needs real closest nodes and always
  rides the wave (``runtime/dht.py _cacheable``).
- **Adaptive replica widening** — :meth:`replica_k` answers 16
  (``widen_k``) for keys in the hot set and 8 (``base_k``) otherwise;
  ``runtime/dht.py`` consults it on the announce walk and the
  calendar-binned republish resolve, so hot keys replicate to
  closest-16 and narrow back to closest-8 on decay.

The cache changes NO protocol state: with it disabled (or missing) every
surface behaves exactly as before, and a cache hit serves the SAME
values the full lookup would return from this node's knowledge — pinned
cache-on == cache-off on runner ops, proxy REST and listeners
(tests/test_hotcache.py + testing/cache_smoke.py), including
put-then-get freshness.  Listens are never cache-served.

Surfaces: ``dht_cache_*`` hit/miss/occupancy/invalidation series +
``dht_cache_hit_ratio`` on the unified registry (``get_metrics()`` +
proxy ``GET /stats``), a ``GET /cache`` proxy snapshot route, the
``cache`` REPL command in tools/dhtnode.py, the ``cache`` section of
``dhtscanner --json``, ``cache_admit``/``cache_invalidate`` flight
events, a degrade-only ``cache_hit_ratio`` health signal and the
``dhtmon --min-cache-hit`` gate.

Import-light by design (the keyspace.py rule): stdlib + the
telemetry/tracing spine at module scope; the device side (ops.
cache_probe, and through it jax) is looked up lazily on first probe,
and a failed backend degrades to a disabled cache instead of failing
the node — serving is identical either way, the cache only
short-circuits.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

import numpy as np

from . import telemetry, tracing

log = logging.getLogger("opendht_tpu.hotcache")

__all__ = ["HotCacheConfig", "HotValueCache"]

# local mirrors of ops.ids constants — ops.ids imports jax at module
# top, so importing them here would defeat the lazy-device design;
# _ensure_device() cross-checks against the real module (the
# keyspace.py convention)
HASH_BYTES = 20
N_LIMBS = 5


# ========================================================== configuration
@dataclass
class HotCacheConfig:
    """Declarative hot-cache configuration (lives on
    ``runtime.config.Config.cache``)."""

    #: master switch; off disables the probe, the fast path and the
    #: widening — results identical either way, the cache only serves
    #: what the full path would
    enabled: bool = True
    #: bounded cache table slots (canonical 20-byte ids on device,
    #: value payloads host-side); admission beyond it evicts the
    #: coldest admitted key first
    capacity: int = 64
    #: max seconds an entry may serve without a refresh (re-admission
    #: from the local store on the observatory tick refreshes it; a
    #: fill-on-get entry with no local backing expires after this)
    entry_ttl: float = 30.0
    #: replica set for keys in the hot set (closest-16; the reference's
    #: k is 8) — the adaptive-widening half of the loop
    widen_k: int = 16
    #: replica set for everything else (routing_table.h:26)
    base_k: int = 8


class _Entry:
    __slots__ = ("key", "values", "expires", "hits", "store_backed")

    def __init__(self, key: bytes, values: list, expires: float,
                 store_backed: bool):
        self.key = key
        self.values = values
        self.expires = expires
        self.hits = 0
        self.store_backed = store_backed


# ============================================================== the cache
class HotValueCache:
    """Bounded device id table + host value payloads (module
    docstring).  One per :class:`~opendht_tpu.runtime.dht.Dht`
    (``dht.hotcache``); standalone construction (no observatory) is the
    unit-test surface — call :meth:`on_keyspace_tick` manually."""

    def __init__(self, cfg: Optional[HotCacheConfig] = None, *,
                 node: str = "",
                 local_values: Optional[Callable] = None,
                 clock: Optional[Callable[[], float]] = None):
        """``local_values(key_bytes) -> list`` (optional) pulls the
        node's current value set for a key at admission/refresh time —
        ``runtime/dht.py`` wires the local store; ``clock`` defaults to
        a monotonic host clock (nodes pass ``scheduler.time``)."""
        import time as _time
        self.cfg = cfg or HotCacheConfig()
        self.node = node
        self._labels = {"node": node} if node else {}
        self._local_values = local_values
        self._clock = clock or _time.monotonic
        self._lock = threading.Lock()
        # host state
        self._entries: Dict[bytes, _Entry] = {}
        self._hot: set = set()          # current keyspace hot set
        # per-key invalidation sequence (freshness tokens): a get in
        # flight across a put must not re-seed the stale pre-put value
        # set through offer() — the offer carries the token captured at
        # get start and is rejected if an invalidate bumped it since
        # (review finding).  Pruned to the hot set on each tick.
        self._inval_seq: Dict[bytes, int] = {}
        # device state (lazy; a failed backend downgrades to disabled)
        self._device_ok: "bool | None" = None if self.cfg.enabled else False
        self._ids_dev = None            # [capacity, 5] uint32
        self._valid_dev = None          # [capacity] bool
        self._slots: List[Optional[bytes]] = []
        self._dirty = True
        # windowed hit ratio (reset per observatory tick): the health
        # signal and the dht_cache_hit_ratio gauge read the LAST
        # window, so a week-old lifetime ratio can't hide a fresh miss
        # storm (the dhtmon --window lesson, cache-side)
        self._win_hits = 0
        self._win_misses = 0
        self._ratio: Optional[float] = None
        # metric handles are registered only for an ENABLED cache — a
        # disabled component must never register permanently-zero
        # series (the round-14 rule the keyspace observatory follows)
        if self.cfg.enabled:
            reg = telemetry.get_registry()
            self._m_hits = reg.counter("dht_cache_hits_total",
                                       **self._labels)
            self._m_misses = reg.counter("dht_cache_misses_total",
                                         **self._labels)
            self._m_admit = reg.counter("dht_cache_admissions_total",
                                        **self._labels)
            self._m_evict = reg.counter("dht_cache_evictions_total",
                                        **self._labels)
            self._m_inval = reg.counter("dht_cache_invalidations_total",
                                        **self._labels)
            self._m_occ = reg.gauge("dht_cache_occupancy", **self._labels)
            self._m_ratio = reg.gauge("dht_cache_hit_ratio",
                                      **self._labels)
            self._m_widened = reg.gauge("dht_cache_widened_keys",
                                        **self._labels)
            reg.gauge("dht_cache_capacity", **self._labels).set(
                self.cfg.capacity)
            self._m_ratio.set(-1.0)     # -1 = unknown (no window yet)

    # ------------------------------------------------------------- device
    @property
    def enabled(self) -> bool:
        return self.cfg.enabled and self._device_ok is not False

    def active(self) -> bool:
        """Whether the wave builder should bother probing: enabled AND
        at least one entry admitted (an empty cache must not cost the
        wave a launch)."""
        return self.enabled and bool(self._entries)

    def _ensure_device(self) -> bool:
        if self._device_ok is not None:
            return self._device_ok
        try:
            from .ops import ids as _ids
            from .ops import cache_probe as _cp   # noqa: F401 (compile probe)
            if (_ids.HASH_BYTES, _ids.N_LIMBS) != (HASH_BYTES, N_LIMBS):
                raise AssertionError(
                    "hotcache constant mirrors drifted from ops.ids")
            self._device_ok = True
        except Exception:
            log.warning("hot-cache probe unavailable (no jax backend?); "
                        "cache disabled", exc_info=True)
            self._device_ok = False
        return self._device_ok

    def _go_dark_locked(self) -> None:
        """Device failure mid-probe: disable AND clear every entry
        (callers hold the lock) — a dead cache must serve nothing and
        report unknown, never a frozen hot set (the keyspace go-dark
        contract)."""
        self._device_ok = False
        self._entries.clear()
        self._hot = set()
        self._slots = []
        self._ids_dev = self._valid_dev = None
        self._ratio = None
        self._win_hits = self._win_misses = 0
        self._dirty = True

    def _rebuild_device_locked(self) -> None:
        """Re-place the id table after a mutation (callers hold the
        lock).  The table is [capacity, 5] uint32 — tiny, so a full
        rebuild per admission/eviction is cheaper than tracking slot
        deltas on device."""
        import jax.numpy as jnp
        from .ops.ids import ids_from_bytes
        cap = max(1, int(self.cfg.capacity))
        keys = list(self._entries)[:cap]
        ids = np.zeros((cap, N_LIMBS), np.uint32)
        if keys:
            ids[:len(keys)] = ids_from_bytes(b"".join(keys))
        valid = np.arange(cap) < len(keys)
        self._ids_dev = jnp.asarray(ids)
        self._valid_dev = jnp.asarray(valid)
        self._slots = keys + [None] * (cap - len(keys))
        self._dirty = False

    # ---------------------------------------------------------- admission
    def on_keyspace_tick(self, top: List[dict]) -> None:
        """The observatory-tick subscription (``KeyspaceObservatory.
        subscribe``): ``top`` is the tick's heavy-hitter list (dicts
        with ``_key`` canonical bytes, ``estimate``, ``hot``).  Admits
        newly-hot keys, refreshes still-hot store-backed entries,
        evicts keys that decayed out of the hot set and sweeps expired
        entries; then rolls the hit-ratio window and refreshes the
        gauges."""
        if not self.enabled:
            return
        now = self._clock()
        hot = [t for t in top if t.get("hot") and t.get("_key")]
        tr = tracing.get_tracer()
        admitted, evicted = [], []
        with self._lock:
            self._hot = set(t["_key"] for t in hot)
            # rank preserves the observatory's estimate order so the
            # capacity bound keeps the HOTTEST keys
            for t in hot[:max(1, int(self.cfg.capacity))]:
                kb = t["_key"]
                ent = self._entries.get(kb)
                values = self._pull_values(kb)
                if ent is None:
                    if values:
                        self._entries[kb] = _Entry(
                            kb, values, now + self.cfg.entry_ttl, True)
                        admitted.append((kb, t))
                        self._dirty = True
                    # a hot key with no local values stays un-admitted;
                    # offer() fills it when a get completes
                elif values:
                    # refresh from the store while hot: the TTL only
                    # ever expires entries with no local backing
                    ent.values = values
                    ent.expires = now + self.cfg.entry_ttl
                    ent.store_backed = True
            # evict: decayed out of the hot set, past capacity, or
            # expired (fill-on-get entries whose backing never
            # materialized)
            for kb in list(self._entries):
                ent = self._entries[kb]
                if kb not in self._hot or ent.expires <= now:
                    del self._entries[kb]
                    evicted.append(kb)
                    self._dirty = True
            while len(self._entries) > max(1, int(self.cfg.capacity)):
                kb = min(self._entries,
                         key=lambda k: self._entries[k].hits)
                del self._entries[kb]
                evicted.append(kb)
                self._dirty = True
            # prune freshness tokens to the hot set: every observed put
            # bumps a key's sequence, and only keys that can be offered
            # (hot ones) need their history kept across the tick
            self._inval_seq = {kb: s for kb, s in self._inval_seq.items()
                               if kb in self._hot}
            # roll the hit-ratio window
            probes = self._win_hits + self._win_misses
            self._ratio = (self._win_hits / probes) if probes else None
            self._win_hits = self._win_misses = 0
        if admitted:
            self._m_admit.inc(len(admitted))
            if tr.enabled:
                for kb, t in admitted:
                    tr.event("cache_admit", node=self.node, key=kb.hex(),
                             estimate=t.get("estimate"),
                             share=t.get("share"))
        if evicted:
            self._m_evict.inc(len(evicted))
        self._export_gauges()

    def _pull_values(self, kb: bytes) -> list:
        if self._local_values is None:
            return []
        try:
            return list(self._local_values(kb) or [])
        except Exception:
            log.exception("hot-cache local-value pull failed")
            return []

    def offer_token(self, key) -> int:
        """The key's current invalidation sequence — capture it BEFORE
        starting a get whose completion may :meth:`offer`; the offer is
        rejected if an invalidate bumped the sequence in between (the
        observed values predate the put)."""
        with self._lock:
            return self._inval_seq.get(bytes(key), 0)

    def offer(self, key, values: list,
              token: Optional[int] = None) -> bool:
        """Fill-on-get (the Kademlia lookup-path caching move): a
        completed get observed values for ``key`` — admit them if the
        key is currently hot and not yet cached.  ``token`` (from
        :meth:`offer_token` at get start) guards freshness: a stale
        token means a put invalidated the key mid-get and these values
        must not re-enter.  Returns True when the offer was taken."""
        if not self.enabled or not values:
            return False
        kb = bytes(key)
        with self._lock:
            if kb not in self._hot or kb in self._entries:
                return False
            if token is not None and token != self._inval_seq.get(kb, 0):
                return False
            self._entries[kb] = _Entry(
                kb, list(values), self._clock() + self.cfg.entry_ttl,
                False)
            self._dirty = True
        self._m_admit.inc()
        tr = tracing.get_tracer()
        if tr.enabled:
            tr.event("cache_admit", node=self.node, key=kb.hex(),
                     source="get_fill")
        self._export_gauges()
        return True

    def wants(self, key) -> bool:
        """Whether :meth:`offer` would take values for this key (a hot,
        not-yet-cached key) — the get path's cheap pre-check."""
        if not self.enabled:
            return False
        kb = bytes(key)
        with self._lock:
            return kb in self._hot and kb not in self._entries

    # --------------------------------------------------------- freshness
    def invalidate(self, key) -> bool:
        """An observed put landed on ``key``: drop the cached entry so
        the NEXT get takes the full path (and re-admission re-reads the
        store) — a stale hit is never served.  Called from
        ``Dht.storage_store`` (local puts, incoming announces) and
        ``Dht.put`` (the origin side, even when the local store
        rejects)."""
        if not self.enabled:
            return False
        kb = bytes(key)
        with self._lock:
            # bump the freshness token even when nothing is cached: an
            # in-flight get's offer must also be rejected when the put
            # lands between admission windows
            self._inval_seq[kb] = self._inval_seq.get(kb, 0) + 1
            ent = self._entries.pop(kb, None)
            if ent is not None:
                self._dirty = True
        if ent is None:
            return False
        self._m_inval.inc()
        tr = tracing.get_tracer()
        if tr.enabled:
            tr.event("cache_invalidate", node=self.node, key=kb.hex())
        self._export_gauges()
        return True

    # ------------------------------------------------------------ serving
    def probe_wave(self, targets, eligible) -> List[Optional[list]]:
        """ONE batched XOR-compare launch over a wave's targets
        (``ops/cache_probe.py``): returns per-target cached value lists
        (None = miss or ineligible).  Only ELIGIBLE targets (pure-get
        refills — the caller decides) are served and counted; the rest
        ride along in the same launch uncounted.  Any device failure
        goes dark: every target reports miss and the cache disables —
        the wave proceeds unchanged, serving is never blocked."""
        n = len(targets)
        out: List[Optional[list]] = [None] * n
        if not self.active() or not self._ensure_device():
            return out
        try:
            from .ops.cache_probe import cache_probe
            from .ops.ids import ids_from_hashes
            with self._lock:
                if self._dirty or self._ids_dev is None:
                    self._rebuild_device_locked()
                ids_dev, valid_dev = self._ids_dev, self._valid_dev
                slots = list(self._slots)
            hit, slot = cache_probe(ids_dev, valid_dev,
                                    ids_from_hashes(targets))
            hit = np.asarray(hit)
            slot = np.asarray(slot)
        except Exception:
            log.exception("hot-cache probe failed; disabling")
            with self._lock:
                self._go_dark_locked()
            self._export_gauges()
            return out
        hits = misses = 0
        with self._lock:
            for i in range(n):
                if not eligible[i]:
                    continue
                ent = None
                if hit[i]:
                    kb = slots[int(slot[i])]
                    # re-check the host dict: an invalidate between the
                    # table rebuild and this scatter must win (freshness
                    # beats the stale device row)
                    ent = self._entries.get(kb) if kb is not None else None
                if ent is not None:
                    ent.hits += 1
                    out[i] = list(ent.values)
                    hits += 1
                else:
                    misses += 1
            self._win_hits += hits
            self._win_misses += misses
        if hits:
            self._m_hits.inc(hits)
        if misses:
            self._m_misses.inc(misses)
        return out

    def serve_one(self, key) -> Optional[list]:
        """Per-op membership test for the batching-off escape hatch
        (``Dht._refill`` when the wave builder is disabled): the host
        dict IS the device table's source of truth, so the decision is
        identical to :meth:`probe_wave`'s (pinned vs the probe_host
        oracle in tests/test_hotcache.py)."""
        if not self.active():
            return None
        kb = bytes(key)
        with self._lock:
            ent = self._entries.get(kb)
            if ent is not None:
                ent.hits += 1
                self._win_hits += 1
                vals = list(ent.values)
            else:
                self._win_misses += 1
                vals = None
        (self._m_hits if vals is not None else self._m_misses).inc()
        return vals

    # --------------------------------------------------- replica widening
    def is_hot(self, key) -> bool:
        if not self.enabled:
            return False
        with self._lock:
            return bytes(key) in self._hot

    def replica_k(self, key) -> int:
        """The adaptive replica set for ``key``: ``widen_k`` (16) while
        the key is in the observatory's hot set, ``base_k`` (8)
        otherwise — announce walks and the calendar-binned republish
        resolve consult this, so hot keys widen and narrow back on
        decay (pinned vs a scalar oracle in tests/test_hotcache.py)."""
        return self.cfg.widen_k if self.is_hot(key) else self.cfg.base_k

    # ---------------------------------------------------------- read side
    def hit_ratio(self) -> Optional[float]:
        """Last completed window's hit ratio (None = unknown: disabled,
        dark, or no probes in the window) — the ``cache_hit_ratio``
        health-signal source and the ``dht_cache_hit_ratio`` gauge."""
        if not self.enabled:
            return None
        with self._lock:
            return self._ratio

    def _export_gauges(self) -> None:
        with self._lock:
            occ = len(self._entries)
            ratio = self._ratio
            widened = len(self._hot)
        self._m_occ.set(occ)
        self._m_ratio.set(-1.0 if ratio is None else ratio)
        self._m_widened.set(widened)

    def snapshot(self) -> dict:
        """JSON-able cache state — the proxy ``GET /cache`` body, the
        ``cache`` REPL command and the scanner section."""
        with self._lock:
            entries = [{
                "key": ent.key.hex(),
                "values": len(ent.values),
                "hits": ent.hits,
                "store_backed": ent.store_backed,
                "ttl_s": round(ent.expires - self._clock(), 1),
            } for ent in sorted(self._entries.values(),
                                key=lambda e: -e.hits)]
            ratio = self._ratio
            hot = [kb.hex() for kb in self._hot]
        if not self.cfg.enabled:
            return {"enabled": False}
        return {
            "enabled": bool(self.enabled),
            "capacity": self.cfg.capacity,
            "occupancy": len(entries),
            "entry_ttl_s": self.cfg.entry_ttl,
            "hit_ratio": (round(ratio, 4) if ratio is not None else None),
            "hits": int(self._m_hits.value),
            "misses": int(self._m_misses.value),
            "admissions": int(self._m_admit.value),
            "evictions": int(self._m_evict.value),
            "invalidations": int(self._m_inval.value),
            "replica_k": {"base": self.cfg.base_k,
                          "widened": self.cfg.widen_k},
            "hot_keys": hot,
            "entries": entries,
        }
