"""DhtProxyServer: REST facade over a running DhtRunner.

Behavioral port of the reference proxy server (reference:
src/dht_proxy_server.cpp:70-93 routes, include/opendht/dht_proxy_server.h):

routes
    ``GET /``                  node info (node id + per-family stats)
    ``STATS /``                server stats (listen/put counts, request rate)
    ``GET /trace``             flight-recorder dump (ISSUE-4)
    ``GET /trace/{id}``        one distributed trace's spans
                               (``?fmt=chrome`` = Perfetto-loadable dump)
    ``GET /{hash}``            stream values as JSON lines
    ``GET /{hash}/{value_id}`` one value by id
    ``LISTEN /{hash}``         long-poll stream of value updates
    ``POST /{hash}``           put a JSON value (``permanent`` supported,
                               with server-side refresh-or-expire
                               bookkeeping, dht_proxy_server.cpp:505-620)
    ``SIGN /{hash}``           sign the posted value with the node identity
    ``ENCRYPT /{hash}?to=``    sign+encrypt the posted value
    ``SUBSCRIBE /{hash}``      register a push listener (push gateway is a
                               pluggable callback — the reference posts to
                               a Gorush instance, :411-469)
    ``UNSUBSCRIBE /{hash}``    drop a push listener
    ``OPTIONS /{hash}``        CORS preflight

Values stream as line-delimited JSON exactly like the reference
(``Json::writeString(...) + "\\n"`` per value, :293).  The server is a
threading HTTP/1.0 server: each streaming request holds one handler
thread, responses are close-delimited.
"""

from __future__ import annotations

import concurrent.futures
import json
import queue
import re
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple
from urllib.parse import urlparse, parse_qs

from .. import telemetry, tracing
from ..infohash import InfoHash
from ..core.value import Value
from .json_codec import value_to_json, value_from_json, permanent_deadline

# reference: proxy::OP_TIMEOUT/OP_MARGIN (include/opendht/proxy.h:25-26) —
# permanent ops expire server-side unless the client refreshes them; a
# refresh push is sent OP_MARGIN before expiry (dht_proxy_server.cpp:462-470).
OP_TIMEOUT = 60 * 60.0
OP_MARGIN = 5 * 60.0
STATS_PERIOD = 120.0            # dht_proxy_server.cpp:138-148

# strict query-param grammars for the round-17 history/trace routes: a
# bare int()/float() accepts Python literal niceties — digit-group
# underscores ('1_5'), sign prefixes ('+5'), surrounding whitespace,
# 'nan'/'inf' — that the malformed-param 400 contract must reject
# (review finding; the same leniency _trace_hex was hardened against)
_Q_INT = re.compile(r"^\d+$")
_Q_NUM = re.compile(r"^\d+(?:\.\d+)?$")


class ServerStats:
    """dht_proxy_server.h:71-116."""

    def __init__(self):
        self.listen_count = 0
        self.put_count = 0
        self.push_listeners_count = 0
        self.request_rate = 0.0
        self.total_requests = 0
        self.node_info: dict = {}

    def to_dict(self) -> dict:
        return {
            "listenCount": self.listen_count,
            "putCount": self.put_count,
            "pushListenersCount": self.push_listeners_count,
            "requestRate": self.request_rate,
            "totalRequests": self.total_requests,
            "nodeInfo": self.node_info,
        }


class _PermanentPut:
    __slots__ = ("value", "deadline", "client_id")

    def __init__(self, value: Value, deadline: float, client_id: str = ""):
        self.value = value
        self.deadline = deadline
        self.client_id = client_id


class _PushListener:
    __slots__ = ("key", "client_id", "token", "deadline",
                 "push_token", "is_android", "client_token", "refresh_sent")

    def __init__(self, key: InfoHash, client_id: str, token, deadline: float,
                 push_token: str = "", is_android: bool = True,
                 client_token: int = 0):
        self.key = key
        self.client_id = client_id
        self.token = token              # backend (runner.listen) token
        self.deadline = deadline
        self.push_token = push_token    # gateway device token (body "key")
        self.is_android = is_android    # body "platform" == "android"
        self.client_token = client_token  # client's token number (body "token")
        self.refresh_sent = False       # expiry-refresh push dispatched


class DhtProxyServer:
    """Serve a DhtRunner over REST (dht_proxy_server.cpp:96-136)."""

    def __init__(self, runner, port: int = 8080, *,
                 push_sender: Optional[Callable[[str, dict], None]] = None,
                 push_server: Optional[str] = None,
                 address: str = "127.0.0.1"):
        """``push_server`` ("host:port") enables the HTTP Gorush gateway
        client (↔ the reference's pushServer ctor arg,
        dht_proxy_server.cpp:96-136); ``push_sender`` is the injectable
        callback alternative, kept for tests and embedding."""
        self._runner = runner
        self._push_sender = push_sender
        self._gorush = None
        if push_server:
            from .push import GorushPushSender
            self._gorush = GorushPushSender(push_server)
        self.stats = ServerStats()
        self._req_times: list = []
        self._lock = threading.Lock()
        # (hash, value_id) -> _PermanentPut   (dht_proxy_server.cpp:505-620)
        self._puts: Dict[Tuple[InfoHash, int], _PermanentPut] = {}
        # (hash, client_id) -> _PushListener  (:411-469)
        self._push_listeners: Dict[Tuple[InfoHash, str], _PushListener] = {}

        handler = _make_handler(self)
        self._httpd = ThreadingHTTPServer((address, port), handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._stop = threading.Event()
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, name="proxy-http", daemon=True)
        self._serve_thread.start()
        self._maint_thread = threading.Thread(
            target=self._maintenance_loop, name="proxy-maint", daemon=True)
        self._maint_thread.start()

    # ------------------------------------------------------------------ api
    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._gorush is not None:
            self._gorush.join()

    def get_stats(self) -> ServerStats:
        return self.stats

    # ------------------------------------------------------------- internal
    def _count_request(self) -> None:
        now = time.monotonic()
        telemetry.get_registry().counter("dht_proxy_requests_total").inc()
        with self._lock:
            self.stats.total_requests += 1
            self._req_times.append(now)
            cutoff = now - 60.0
            while self._req_times and self._req_times[0] < cutoff:
                self._req_times.pop(0)
            self.stats.request_rate = len(self._req_times) / 60.0

    def prometheus_stats(self) -> str:
        """Text exposition for ``GET /stats`` (ISSUE-3: the reference's
        ``STATS /`` server-stats island joined to the unified registry).
        Refreshes the ServerStats gauges and — when the runner exposes
        ``get_metrics`` — the routing-table gauges, then dumps the whole
        process registry."""
        reg = telemetry.get_registry()
        with self._lock:
            reg.gauge("dht_proxy_listen_count").set(self.stats.listen_count)
            reg.gauge("dht_proxy_put_count").set(self.stats.put_count)
            reg.gauge("dht_proxy_push_listeners").set(
                self.stats.push_listeners_count)
            reg.gauge("dht_proxy_request_rate").set(self.stats.request_rate)
        get_metrics = getattr(self._runner, "get_metrics", None)
        if get_metrics is not None:
            try:
                get_metrics()        # refresh dht_routing_* gauges
            except Exception:
                pass
        return reg.prometheus()

    def _node_info(self) -> dict:
        """GET / payload (dht_proxy_server.cpp:206-232)."""
        import socket as _s
        r = self._runner
        info = {"node_id": r.get_node_id().hex(), "id": r.get_id().hex()}
        try:
            info["ipv4"] = r.get_node_stats(_s.AF_INET).to_dict()
        except Exception:
            info["ipv4"] = {}
        try:
            info["ipv6"] = r.get_node_stats(_s.AF_INET6).to_dict()
        except Exception:
            info["ipv6"] = {}
        try:
            # round-12 ingest surface: the wave builder's coalescing
            # health next to the routing stats (queue depth, occupancy
            # percentiles, sheds) — the JSON sibling of the
            # dht_ingest_* series GET /stats exports
            info["ingest"] = r._dht.wave_builder.snapshot()
        except Exception:
            info["ingest"] = {}
        return info

    def _maintenance_loop(self) -> None:
        """Expire unrefreshed permanent puts and push listeners; refresh
        the stats snapshot (dht_proxy_server.cpp:138-148, :560-620)."""
        last_stats = 0.0
        while not self._stop.wait(1.0):
            now = time.monotonic()
            with self._lock:
                expired_puts = [(k, p) for k, p in self._puts.items()
                                if p.deadline <= now]
                for k, _ in expired_puts:
                    del self._puts[k]
                expired_push = [k for k, l in self._push_listeners.items()
                                if l.deadline <= now]
                push_expired_records = [self._push_listeners.pop(k)
                                        for k in expired_push]
                self.stats.put_count = len(self._puts)
                self.stats.push_listeners_count = len(self._push_listeners)
            for (key, vid), _ in expired_puts:
                try:
                    self._runner.cancel_put(key, vid)
                except Exception:
                    pass
            for rec in push_expired_records:
                if rec.token is None:   # backend listen still registering;
                    continue            # do_SUBSCRIBE's re-check cancels it
                try:
                    self._runner.cancel_listen(rec.key, rec.token)
                except Exception:
                    pass
            # refresh pushes: OP_MARGIN before a listener expires, tell
            # the client to re-subscribe (dht_proxy_server.cpp:462-470:
            # expireNotifyJob sends {"timeout": key, "to", "token"})
            with self._lock:
                refresh = [l for l in self._push_listeners.values()
                           if not l.refresh_sent
                           and l.deadline - OP_MARGIN <= now]
                for l in refresh:
                    l.refresh_sent = True
            for rec in refresh:
                self._notify_push(rec, {
                    "timeout": rec.key.hex(),
                    "to": rec.client_id,
                    "token": str(rec.client_token),
                })
            if now - last_stats >= STATS_PERIOD or last_stats == 0.0:
                last_stats = now
                try:
                    self.stats.node_info = self._node_info()
                except Exception:
                    pass

    # Push notifications: the Gorush HTTP gateway gets the reference's
    # exact data shape (dht_proxy_server.cpp:446-470); the injected
    # callback additionally receives `extra` (value ids) for embedders.
    def _notify_push(self, rec: _PushListener, data: dict,
                     extra: Optional[dict] = None) -> None:
        if self._gorush is not None and rec.push_token:
            try:
                self._gorush.notify(rec.push_token, data, rec.is_android)
            except Exception:
                pass
        if self._push_sender is not None:
            try:
                self._push_sender(rec.client_id,
                                  dict(data, **extra) if extra else data)
            except Exception:
                pass


def _make_handler(server: DhtProxyServer):
    runner = server._runner

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.0"
        server_version = "OpenDhtTpuProxy/1.0"

        # silence default stderr logging
        def log_message(self, fmt, *args):
            pass

        # ------------------------------------------------------- helpers
        def _parse(self):
            u = urlparse(self.path)
            parts = [p for p in u.path.split("/") if p]
            return parts, parse_qs(u.query)

        def _send_json(self, obj, code: int = 200) -> None:
            body = (json.dumps(obj) + "\n").encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Access-Control-Allow-Origin", "*")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _err(self, code: int, msg: str) -> None:
            self._send_json({"err": msg}, code)

        def _read_body_json(self) -> Optional[dict]:
            try:
                n = int(self.headers.get("Content-Length", "0"))
                raw = self.rfile.read(n) if n else b"{}"
                obj = json.loads(raw.decode() or "{}")
                return obj if isinstance(obj, dict) else None
            except Exception:
                return None

        def _hash_arg(self, parts) -> Optional[InfoHash]:
            if not parts:
                return None
            try:
                h = InfoHash(parts[0])
            except Exception:
                # reference hashes any non-hex key (dht_proxy_client
                # semantics); keep strict-hex here like the server.
                return None
            if not h:
                return None
            return h

        def _begin_stream(self) -> None:
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Access-Control-Allow-Origin", "*")
            self.end_headers()

        def _write_line(self, obj) -> bool:
            try:
                self.wfile.write((json.dumps(obj) + "\n").encode())
                self.wfile.flush()
                return True
            except (BrokenPipeError, ConnectionResetError, OSError):
                return False

        # --------------------------------------------------------- routes
        def do_OPTIONS(self):
            self.send_response(200)
            self.send_header("Access-Control-Allow-Origin", "*")
            self.send_header(
                "Access-Control-Allow-Methods",
                "OPTIONS, GET, POST, LISTEN, SIGN, ENCRYPT, "
                "SUBSCRIBE, UNSUBSCRIBE, STATS")
            self.send_header("Access-Control-Allow-Headers", "content-type")
            self.send_header("Content-Length", "0")
            self.end_headers()

        def do_GET(self):
            server._count_request()
            parts, _q = self._parse()
            if not parts:                      # GET / → node info (:206-232)
                self._send_json(server._node_info())
                return
            if parts == ["healthz"]:
                # GET /healthz → readiness probe (ISSUE-9): 200 when the
                # node's health verdict is healthy/degraded (serving,
                # possibly impaired), 503 when unhealthy or unknown
                # (disconnected, pre-first-tick, or health disabled) —
                # k8s/LB readiness semantics, with the full verdict +
                # per-signal/SLO attribution as the JSON body.  Like
                # /stats, "healthz" is not a valid hash so the path was
                # previously a 400 and stays unambiguous.
                rep = {}
                try:
                    rep = runner.get_health()
                except Exception:
                    pass
                verdict = rep.get("verdict", "unknown")
                ready = verdict in ("healthy", "degraded")
                body = {"ready": ready, "verdict": verdict,
                        "node_id": runner.get_node_id().hex(),
                        "status": runner.get_status().name,
                        "health": rep}
                self._send_json(body, 200 if ready else 503)
                return
            if parts == ["stats"]:
                # GET /stats → Prometheus text exposition of the unified
                # telemetry registry (ISSUE-3; extends the reference's
                # STATS / JSON route — "stats" is not a valid hash, so
                # the path was previously a 400 and stays unambiguous)
                body = server.prometheus_stats().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Access-Control-Allow-Origin", "*")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if parts == ["keyspace"]:
                # GET /keyspace → the keyspace traffic observatory
                # snapshot (ISSUE-10): 256-bin keyspace histogram,
                # heavy-hitter top-K with windowed estimates/shares +
                # hot flags, per-shard load attribution and the
                # imbalance ratio.  "keyspace" is not a valid hash, so
                # — like /stats — the path was previously a 400 and
                # stays unambiguous.
                # get_keyspace already degrades to {"enabled": False}
                # on any internal failure — no second wrapper here
                self._send_json(runner.get_keyspace())
                return
            if parts == ["cache"]:
                # GET /cache → the hot-key serving cache snapshot
                # (ISSUE-11): occupancy, per-entry hit counts, windowed
                # hit ratio, invalidations and the widened hot set.
                # "cache" is not a valid hash, so — like /stats — the
                # path was previously a 400 and stays unambiguous.
                # get_cache already degrades to {"enabled": False} on
                # any internal failure — no second wrapper here
                self._send_json(runner.get_cache())
                return
            if parts == ["reshard"]:
                # GET /reshard → the load-aware resharding snapshot
                # (ISSUE-17): layout generation + solved edges,
                # tick/swap/reason-labeled skip counters, sustain latch
                # age and post-swap refolded imbalance.  "reshard" is
                # not a valid hash, so — like /stats — the path was
                # previously a 400 and stays unambiguous.
                # get_reshard already degrades to {"enabled": False} on
                # any internal failure — no second wrapper here
                self._send_json(runner.get_reshard())
                return
            if parts == ["history"]:
                # GET /history[?since=SEC][&limit=N] → the round-17
                # flight data recorder's retained frames (delta-encoded
                # registry history) with the server clocks for skew
                # estimation — what dhtmon --window/--since and the
                # timeline assembler consume instead of
                # scrape-diff-scrape.  "history" is not a valid hash,
                # so — like /stats — the path was previously a 400 and
                # stays unambiguous.
                since = limit = None
                sq = (_q.get("since") or [None])[0]
                lq = (_q.get("limit") or [None])[0]
                if sq is not None:
                    if not _Q_NUM.match(sq):
                        self._err(400, "invalid since/limit")
                        return
                    since = float(sq)
                if lq is not None:
                    if not _Q_INT.match(lq):
                        self._err(400, "invalid since/limit")
                        return
                    limit = int(lq)
                self._send_json(runner.get_history(since=since,
                                                   limit=limit))
                return
            if parts == ["debug", "bundle"]:
                # GET /debug/bundle → a fresh post-mortem black-box
                # bundle (round 17): last-N history frames + flight
                # ring + kernel ledger + keyspace/cache snapshots in
                # one artifact (summaries of the auto-captured bundles
                # ride along under "auto_captures").  "debug" is not a
                # valid hash, so the path was previously a 400 and
                # stays unambiguous.
                self._send_json(runner.dump_bundle())
                return
            if parts == ["profile"]:
                # GET /profile → the per-op latency waterfall (round
                # 19, ISSUE-15): per-stage dht_stage_seconds histograms
                # with p50/p95/p99 + bucket exemplars, the stage
                # budgets, the per-op decomposition ring and the live
                # OPEN-bound comparison; ?fmt=folded serves
                # flamegraph-shaped folded stacks as text/plain
                # ("stack weight" lines for flamegraph.pl/speedscope).
                # "profile" is not a valid hash, so — like /stats —
                # the path was previously a 400 and stays unambiguous.
                fmt = (_q.get("fmt") or [None])[0]
                if fmt == "folded":
                    from .. import waterfall as _wf
                    body = _wf.get_profiler().folded().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Access-Control-Allow-Origin", "*")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if fmt is not None:
                    self._err(400, "invalid fmt")
                    return
                # get_profile already degrades to {"enabled": False}
                # on any internal failure — no second wrapper here
                self._send_json(runner.get_profile())
                return
            if parts == ["pipeline"]:
                # GET /pipeline → the pipeline utilization observatory
                # (round 22, ISSUE-18): windowed device occupancy,
                # per-cause bubble attribution, measured fill∥device
                # overlap and the pipeline shape; ?fmt=trace serves
                # the Perfetto lane export (one pid per fill/device/
                # drain lane, waves as slices linked to their
                # dht.search.wave spans).  "pipeline" is not a valid
                # hash, so — like /profile — the path was previously a
                # 400 and stays unambiguous.
                fmt = (_q.get("fmt") or [None])[0]
                if fmt == "trace":
                    self._send_json(runner.get_pipeline_trace())
                    return
                if fmt is not None:
                    self._err(400, "invalid fmt")
                    return
                # get_pipeline already degrades to {"enabled": False}
                self._send_json(runner.get_pipeline())
                return
            if parts == ["listeners"]:
                # GET /listeners → the wave-scale listener-table
                # snapshot (round 24): occupancy/tombstones/overflow,
                # buffered puts, match/delivery counters and the
                # windowed delivery-lag p95.  "listeners" is not a
                # valid hash, so — like /stats — the route cannot
                # shadow a key fetch.  get_listeners already degrades
                # to {"enabled": False} on a node without the table.
                self._send_json(runner.get_listeners())
                return
            if parts == ["peers"]:
                # GET /peers → the per-peer network observatory
                # (round 23, ISSUE-19): per-peer srtt/rttvar/RTO,
                # request outcome counts, attempt timeouts + spurious
                # retransmits, bytes by message type and status flap
                # transitions — the wire-map assembler's scrape
                # surface.  "peers" is not a valid hash, so — like
                # /stats — the path was previously a 400 and stays
                # unambiguous.
                # get_peers already degrades to {"enabled": False} on
                # any internal failure — no second wrapper here
                self._send_json(runner.get_peers())
                return
            if parts[0] == "trace":
                # GET /trace[?name=] → the node's flight-recorder dump
                # (ISSUE-4; the reference's dumpTables as a scrapeable
                # surface), name-filterable like the REPL's
                # `dump [n] [name]` and get_flight_recorder(name=)
                # (ISSUE-10 satellite: the filter was previously
                # REPL-only — tr.dump() took no args here);
                # GET /trace/<id> → one trace's span list, or the
                # Perfetto-loadable Chrome dump with ?fmt=chrome.
                # "trace" is not a valid hash, so — like /stats — the
                # path was previously a 400 and stays unambiguous.
                # ?limit=N pagination (round-17 satellite): a full ring
                # dump over the proxy was unbounded; limit keeps the
                # NEWEST N spans and events.  Malformed (non-integer /
                # negative) limits are a 400, matching the
                # malformed-trace-id contract below.
                limit = None
                lq = (_q.get("limit") or [None])[0]
                if lq is not None:
                    if not _Q_INT.match(lq):
                        self._err(400, "invalid limit")
                        return
                    limit = int(lq)
                tr = tracing.get_tracer()
                if len(parts) == 1:
                    d = tr.dump(name=(_q.get("name") or [None])[0])
                    if limit is not None:
                        d["spans"] = d["spans"][-limit:] if limit else []
                        d["events"] = d["events"][-limit:] if limit else []
                        d["limit"] = limit
                    self._send_json(d)
                    return
                # a malformed (non-hex / oversized) trace id is a 400,
                # not an empty span list — only a WELL-FORMED unknown
                # id reports {"spans": []} (ISSUE-10 satellite; the two
                # cases were previously indistinguishable)
                if tracing._trace_hex(parts[1]) is None:
                    self._err(400, "invalid trace id")
                elif _q.get("fmt", [""])[0] == "chrome":
                    spans = tr.spans(parts[1])
                    if limit is not None:
                        spans = spans[-limit:] if limit else []
                    self._send_json(tracing.to_chrome_trace(spans))
                else:
                    spans = tr.spans(parts[1])
                    if limit is not None:
                        spans = spans[-limit:] if limit else []
                    self._send_json({"trace_id": parts[1],
                                     "spans": spans})
                return
            key = self._hash_arg(parts)
            if key is None:
                self._err(400, "invalid hash")
                return
            vid: Optional[int] = None
            if len(parts) > 1:                 # GET /{hash}/{vid} (:655-700)
                try:
                    vid = int(parts[1])
                except ValueError:
                    self._err(400, "invalid value id")
                    return
            done = threading.Event()
            lines: "queue.Queue" = queue.Queue()

            def get_cb(values):
                for v in values:
                    if vid is None or v.id == vid:
                        lines.put(v)
                return True

            def done_cb(ok, nodes):
                done.set()

            runner.get(key, get_cb, done_cb)
            self._begin_stream()
            ok = True
            while ok and not (done.is_set() and lines.empty()):
                try:
                    v = lines.get(timeout=0.05)
                except queue.Empty:
                    continue
                ok = self._write_line(value_to_json(v))

        def do_STATS(self):
            server._count_request()
            server.stats.node_info = server._node_info()
            self._send_json(server.stats.to_dict())

        def do_LISTEN(self):
            """Long-poll value stream (dht_proxy_server.cpp:320-409)."""
            server._count_request()
            parts, _q = self._parse()
            key = self._hash_arg(parts)
            if key is None:
                self._err(400, "invalid hash")
                return
            updates: "queue.Queue" = queue.Queue()

            def cb(values, expired):
                # round 24 (ISSUE-20): the batched listener path
                # delivers a wave's values as ONE callback — enqueue
                # the batch as a unit so the stream writer wakes once
                # per wave per stream (wire format unchanged: still
                # one JSON line per value, in delivery order)
                updates.put((list(values), expired))
                return True

            token_fut = runner.listen(key, cb)
            # 0 sentinel (round 12): the backend listen was shed at
            # ingest admission — no subscription exists, so fail the
            # request instead of streaming heartbeats forever.  Short
            # wait only: while the node is still bootstrapping the
            # listen op is legitimately queued (normal-op gating), and
            # the pre-round-12 behavior — start streaming, subscription
            # materializes when the node connects — must be preserved.
            try:
                if token_fut.result(2.0) == 0:
                    self._err(503, "listen shed by ingest backpressure")
                    return
            except concurrent.futures.TimeoutError:
                pass                     # still queued: stream as before
            except Exception:
                self._err(500, "listen failed")
                return
            with server._lock:
                server.stats.listen_count += 1
            self._begin_stream()
            try:
                alive = True
                while alive:
                    try:
                        batch, expired = updates.get(timeout=1.0)
                    except queue.Empty:
                        # heartbeat so dead peers are detected
                        alive = self._write_line({"t": int(time.time())})
                        continue
                    for v in batch:
                        obj = value_to_json(v)
                        if expired:        # expired marker (:741-748)
                            obj["expired"] = True
                        alive = self._write_line(obj)
                        if not alive:
                            break
            finally:
                with server._lock:
                    server.stats.listen_count -= 1
                try:
                    runner.cancel_listen(key, token_fut)
                except Exception:
                    pass

        def do_POST(self):
            """Put a value (dht_proxy_server.cpp:471-620)."""
            server._count_request()
            parts, _q = self._parse()
            key = self._hash_arg(parts)
            if key is None:
                self._err(400, "invalid hash")
                return
            obj = self._read_body_json()
            if obj is None:
                self._err(400, "invalid json")
                return
            try:
                value = value_from_json(obj)
            except Exception:
                self._err(400, "invalid value")
                return
            timeout = permanent_deadline(obj, OP_TIMEOUT)
            permanent = timeout is not None
            done: "queue.Queue" = queue.Queue()
            runner.put(key, value,
                       lambda ok, nodes: done.put(bool(ok)),
                       permanent=permanent)
            try:
                ok = done.get(timeout=30.0)
            except queue.Empty:
                ok = None   # unknown: the put may still land on the DHT
            # track refresh bookkeeping unless the DHT definitively
            # rejected the put; an unknown (timed-out) permanent put is
            # recorded so the maintenance sweep cancels it at deadline
            # instead of leaking it on the DHT forever
            if ok is not False and permanent and value.id != Value.INVALID_ID:
                with server._lock:
                    server._puts[(key, value.id)] = _PermanentPut(
                        value, time.monotonic() + timeout)
                    server.stats.put_count = len(server._puts)
            if ok:
                self._send_json(value_to_json(value))
            else:
                self._err(502, "put failed")

        def do_SIGN(self):
            """dht_proxy_server.cpp:707-760."""
            server._count_request()
            parts, _q = self._parse()
            key = self._hash_arg(parts)
            obj = self._read_body_json()
            if key is None or obj is None:
                self._err(400, "invalid request")
                return
            try:
                value = value_from_json(obj)
                sdht = runner._dht          # SecureDht façade
                sdht.sign(value)
                self._send_json(value_to_json(value))
            except Exception as e:
                self._err(500, "sign failed: %s" % e)

        def do_ENCRYPT(self):
            """dht_proxy_server.cpp:762-820: body carries ``to``."""
            server._count_request()
            parts, q = self._parse()
            key = self._hash_arg(parts)
            obj = self._read_body_json()
            if key is None or obj is None:
                self._err(400, "invalid request")
                return
            to_hex = obj.pop("to", None) or (q.get("to") or [None])[0]
            if not to_hex:
                self._err(400, "missing 'to'")
                return
            try:
                value = value_from_json(obj)
                sdht = runner._dht
                done: "queue.Queue" = queue.Queue()

                def on_pk(pk):
                    try:
                        if pk is None:
                            done.put(None)
                        else:
                            sdht.sign(value)
                            done.put(sdht.encrypt(value, pk))
                    except Exception:
                        done.put(None)

                runner.find_public_key(InfoHash(to_hex), on_pk)
                ev = done.get(timeout=30.0)
                if ev is None:
                    self._err(404, "recipient key not found")
                else:
                    self._send_json(value_to_json(ev))
            except Exception as e:
                self._err(500, "encrypt failed: %s" % e)

        def do_SUBSCRIBE(self):
            """Register a push listener (dht_proxy_server.cpp:411-469)."""
            server._count_request()
            parts, _q = self._parse()
            key = self._hash_arg(parts)
            obj = self._read_body_json()
            if key is None or obj is None:
                self._err(400, "invalid request")
                return
            client_id = str(obj.get("client_id", ""))
            if not client_id:
                self._err(400, "missing client_id")
                return
            # gateway fields (dht_proxy_server.cpp:404-412): "key" is the
            # device push token, "platform" selects android/ios payloads,
            # "token" is the client's own listen-token number
            push_token = str(obj.get("key", ""))
            is_android = str(obj.get("platform", "android")) == "android"
            try:
                client_token = int(obj.get("token", 0) or 0)
            except (TypeError, ValueError):
                client_token = 0
            # reserve the slot under the lock so concurrent subscribes for
            # the same (key, client_id) can't both register a listener
            rec = _PushListener(key, client_id, None,
                                time.monotonic() + OP_TIMEOUT,
                                push_token=push_token, is_android=is_android,
                                client_token=client_token)
            with server._lock:
                existing = server._push_listeners.get((key, client_id))
                if existing is not None:       # refresh (:436-442)
                    existing.deadline = time.monotonic() + OP_TIMEOUT
                    existing.refresh_sent = False
                    existing.push_token = push_token or existing.push_token
                    existing.is_android = is_android
                    if client_token:
                        existing.client_token = client_token
                else:
                    server._push_listeners[(key, client_id)] = rec
                    server.stats.push_listeners_count = \
                        len(server._push_listeners)
            if existing is not None:
                self._send_json(
                    {"token": existing.client_token or id(existing)})
                return

            def cb(values, expired):
                # reference data shape :446-453; ids/expired ride along
                # for the injected-callback embedders.  One _notify_push
                # per callback: with the round-24 batched listener path
                # a whole wave's values arrive as ONE callback, so this
                # is one push dispatch per wave per subscription
                server._notify_push(
                    rec,
                    {"key": key.hex(), "to": client_id,
                     "token": str(rec.client_token)},
                    extra={"expired": bool(expired),
                           "ids": [v.id for v in values]})
                return True

            rec.token = runner.listen(key, cb)
            try:
                # 0 sentinel (round 12): shed at ingest admission — the
                # push subscription does not exist; drop the reserved
                # slot and tell the client instead of returning a token
                # that will never deliver.  Short wait only: a listen
                # still queued behind bootstrap gating keeps the
                # pre-round-12 register-asynchronously behavior.
                if rec.token.result(2.0) == 0:
                    with server._lock:
                        if server._push_listeners.get(
                                (key, client_id)) is rec:
                            del server._push_listeners[(key, client_id)]
                            server.stats.push_listeners_count = \
                                len(server._push_listeners)
                    self._err(503, "listen shed by ingest backpressure")
                    return
            except concurrent.futures.TimeoutError:
                pass                     # still queued: register as before
            except Exception:
                self._err(500, "listen failed")
                return
            # a concurrent UNSUBSCRIBE (or expiry sweep) may have removed
            # the record while the backend listen was registering; tear
            # the fresh listener down instead of leaking it
            with server._lock:
                still_mine = server._push_listeners.get(
                    (key, client_id)) is rec
            if not still_mine:
                try:
                    runner.cancel_listen(key, rec.token)
                except Exception:
                    pass
                self._err(410, "unsubscribed")
                return
            self._send_json({"token": rec.client_token or id(rec)})

        def do_UNSUBSCRIBE(self):
            """dht_proxy_server.cpp:548-554."""
            server._count_request()
            parts, _q = self._parse()
            key = self._hash_arg(parts)
            obj = self._read_body_json()
            if key is None or obj is None:
                self._err(400, "invalid request")
                return
            client_id = str(obj.get("client_id", ""))
            with server._lock:
                rec = server._push_listeners.pop((key, client_id), None)
                server.stats.push_listeners_count = len(server._push_listeners)
            if rec is not None and rec.token is not None:
                try:
                    runner.cancel_listen(rec.key, rec.token)
                except Exception:
                    pass
            self._send_json({"ok": rec is not None})

    return Handler
