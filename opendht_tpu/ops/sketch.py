"""Device-resident count-min sketch over 160-bit id traffic (ISSUE-10).

Kademlia's original design calls for detecting popular keys so hot
spots can be relieved by caching along the lookup path (Maymounkov &
Mazières 2002, §4.1); the textbook streaming structure for "how often
did THIS key occur" under bounded memory is the count-min sketch
(Cormode & Muthukrishnan 2005): a ``[depth, width]`` counter matrix,
one pairwise-independent-ish hash row each, point estimate = min over
rows.  Guarantees (classic CMS):

- never an UNDERestimate: ``estimate(x) >= true(x)`` always (each row
  counts every occurrence of ``x`` plus its colliders);
- overestimate bounded: ``estimate(x) <= true(x) + eps * T`` with
  probability ``1 - delta`` for ``eps = e/width``, ``delta =
  e^-depth`` (T = total stream length) — pinned against an exact
  host-side ``Counter`` oracle in tests/test_keyspace.py.

Here the sketch is a DEVICE structure updated by one batched
scatter-add launch per ingest wave (runtime/wave_builder.py feeds the
wave's ``[Q]`` target ids), because the ids already exist as uint32
limb vectors (:mod:`opendht_tpu.ops.ids`) and the update amortizes
exactly like every other wave kernel: Q ids cost one launch, not Q.
The same launch maintains a 256-bin top-8-bit keyspace histogram —
lexicographic limb order == keyspace order (ids.py), so bin ``b``
covers the contiguous id range ``[b << 152, (b+1) << 152)`` and the
histogram IS the traffic density over the ring, foldable over the
t-sharded table's row boundaries for per-shard load attribution
(opendht_tpu/keyspace.py).

All counters are int32; windowing is exponential decay
(:func:`sketch_decay`, float32 scale + floor — exact for counts below
2^24, far above any decayed window).  The tp twin
(``parallel/sharded.py sharded_sketch_update``) updates per-shard
partial sketches and merges them with one psum pair — integer adds are
associative, so the merged sketch is bit-identical to the
single-device one (pinned in tests/test_keyspace.py).

Host-side mirrors (``hash_columns_host``) use the same mixing
constants so tests can cross-check column placement without a device.
"""

from __future__ import annotations

import functools

import numpy as np

from .ids import N_LIMBS

#: defaults — depth 4 / width 2048 gives eps ~= e/2048 ~= 0.13% of the
#: window total per estimate at delta ~= e^-4 ~= 1.8%, in 32 KB of HBM
SKETCH_DEPTH = 4
SKETCH_WIDTH = 2048
#: the keyspace histogram is always top-8-bit: 256 contiguous ranges
BINS = 256
BIN_BITS = 8

#: per-row seed constants (murmur3/xxhash-style mixing primes); depth
#: is capped by the seed table — 8 rows is already delta ~= 0.03%
_ROW_SEEDS = (0x9747B28C, 0x41C64E6D, 0x6C078965, 0x85EBCA6B,
              0xC2B2AE35, 0x27D4EB2F, 0x165667B1, 0x2545F491)
_MUL1 = 0xCC9E2D51
_MUL2 = 0x1B873593
MAX_DEPTH = len(_ROW_SEEDS)


def _check_geometry(depth: int, width: int) -> None:
    if not 1 <= depth <= MAX_DEPTH:
        raise ValueError(f"sketch depth {depth} outside [1, {MAX_DEPTH}]")
    if width < 2 or width & (width - 1):
        raise ValueError(f"sketch width {width} must be a power of two >= 2")


def hash_columns(ids, depth: int = SKETCH_DEPTH,
                 width: int = SKETCH_WIDTH):
    """Per-row column indices for each id: uint32 ``[..., 5]`` →
    int32 ``[..., depth]`` in ``[0, width)``.

    Each row d folds the 5 limbs through a murmur-style mix (xor,
    odd-constant multiply, rotate) seeded per row, then finalizes with
    the murmur3 fmix avalanche.  All ops are uint32 (wrapping), so the
    device and host mirrors agree bit-for-bit."""
    import jax.numpy as jnp
    _check_geometry(depth, width)
    u = jnp.uint32
    x = ids.astype(u)
    cols = []
    for d in range(depth):
        h = jnp.full(x.shape[:-1], _ROW_SEEDS[d], u)
        for limb in range(N_LIMBS):
            k = x[..., limb] * u(_MUL1)
            k = ((k << u(15)) | (k >> u(17))) * u(_MUL2)
            h = h ^ k
            h = ((h << u(13)) | (h >> u(19))) * u(5) + u(0xE6546B64)
        h = h ^ (h >> u(16))
        h = h * u(0x85EBCA6B)
        h = h ^ (h >> u(13))
        h = h * u(0xC2B2AE35)
        h = h ^ (h >> u(16))
        cols.append((h & u(width - 1)).astype(jnp.int32))
    return jnp.stack(cols, axis=-1)


def hash_columns_host(ids, depth: int = SKETCH_DEPTH,
                      width: int = SKETCH_WIDTH) -> np.ndarray:
    """Numpy mirror of :func:`hash_columns` (same constants, same
    wrapping arithmetic) — the tests' oracle for column placement."""
    _check_geometry(depth, width)
    x = np.asarray(ids, np.uint32)
    M = np.uint32(0xFFFFFFFF)
    cols = np.empty(x.shape[:-1] + (depth,), np.int32)
    with np.errstate(over="ignore"):
        for d in range(depth):
            h = np.full(x.shape[:-1], _ROW_SEEDS[d], np.uint64)
            for limb in range(N_LIMBS):
                k = (x[..., limb].astype(np.uint64) * _MUL1) & M
                k = (((k << np.uint64(15)) | (k >> np.uint64(17))) & M
                     ) * _MUL2 & M
                h = h ^ k
                h = ((((h << np.uint64(13)) | (h >> np.uint64(19))) & M)
                     * 5 + 0xE6546B64) & M
            h = h ^ (h >> np.uint64(16))
            h = (h * 0x85EBCA6B) & M
            h = h ^ (h >> np.uint64(13))
            h = (h * 0xC2B2AE35) & M
            h = h ^ (h >> np.uint64(16))
            cols[..., d] = (h & np.uint64(width - 1)).astype(np.int32)
    return cols


def sketch_init(depth: int = SKETCH_DEPTH, width: int = SKETCH_WIDTH):
    """Fresh ``(sketch [depth, width] int32, hist [BINS] int32)`` pair
    on the default device."""
    import jax.numpy as jnp
    _check_geometry(depth, width)
    return (jnp.zeros((depth, width), jnp.int32),
            jnp.zeros((BINS,), jnp.int32))


@functools.lru_cache(maxsize=8)
def _build_update(depth: int, width: int):
    import jax
    import jax.numpy as jnp

    def fn(sketch, hist, ids):
        q = ids.reshape(-1, N_LIMBS)
        cols = hash_columns(q, depth, width)           # [Q, depth]
        rows = jnp.broadcast_to(
            jnp.arange(depth, dtype=jnp.int32), cols.shape)
        sketch = sketch.at[rows.reshape(-1), cols.reshape(-1)].add(1)
        bins = (q[:, 0] >> jnp.uint32(32 - BIN_BITS)).astype(jnp.int32)
        hist = hist.at[bins].add(1)
        return sketch, hist
    return jax.jit(fn)


def sketch_update(sketch, hist, ids):
    """ONE batched scatter-add launch over a wave's ids: every id
    increments its ``depth`` sketch cells and its top-8-bit histogram
    bin.  ``ids``: uint32 ``[Q, 5]`` (any leading shape; flattened).
    Returns the updated ``(sketch, hist)`` (functional — callers swap
    their references).  Dispatch is async; nothing here blocks."""
    return _build_update(int(sketch.shape[0]), int(sketch.shape[1]))(
        sketch, hist, ids)


@functools.lru_cache(maxsize=8)
def _build_query(depth: int, width: int):
    import jax
    import jax.numpy as jnp

    def fn(sketch, ids):
        q = ids.reshape(-1, N_LIMBS)
        cols = hash_columns(q, depth, width)           # [Q, depth]
        rows = jnp.broadcast_to(
            jnp.arange(depth, dtype=jnp.int32), cols.shape)
        vals = sketch[rows, cols]                      # [Q, depth]
        return jnp.min(vals, axis=-1)
    return jax.jit(fn)


def sketch_query(sketch, ids):
    """Point estimates for a batch of ids: int32 ``[Q]`` = min over
    the ``depth`` rows — the classic CMS read (>= true count, always;
    overestimate bound pinned in tests/test_keyspace.py)."""
    return _build_query(int(sketch.shape[0]), int(sketch.shape[1]))(
        sketch, ids)


@functools.lru_cache(maxsize=8)
def _build_decay(depth: int, width: int, factor: float):
    import jax
    import jax.numpy as jnp

    def fn(sketch, hist):
        f = jnp.float32(factor)
        s = jnp.floor(sketch.astype(jnp.float32) * f).astype(jnp.int32)
        h = jnp.floor(hist.astype(jnp.float32) * f).astype(jnp.int32)
        return s, h
    return jax.jit(fn)


def sketch_decay(sketch, hist, factor: float):
    """Exponential decay: scale every counter by ``factor`` (floor) so
    the sketch holds a WINDOW of recent traffic, not a lifetime sum —
    a key hot yesterday decays out geometrically while the
    overestimate invariant (estimate >= decayed true count) is
    preserved, since floor is monotone and applied uniformly.  Exact
    for counts below 2^24 (float32 mantissa)."""
    if not 0.0 <= factor <= 1.0:
        raise ValueError(f"decay factor {factor} outside [0, 1]")
    return _build_decay(int(sketch.shape[0]), int(sketch.shape[1]),
                        float(factor))(sketch, hist)
