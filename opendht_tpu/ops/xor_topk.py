"""Exact batched XOR-distance top-k over node-ID matrices.

This kernel replaces the reference's scalar per-search closest-node scans
(``RoutingTable::findClosestNodes`` src/routing_table.cpp:109-150 and
``NodeCache::getCachedNodes`` src/node_cache.cpp:41-74) with one batched
scan: Q query ids × N table ids → the k XOR-closest table entries per
query, *exactly*, including the reference's bytewise-lexicographic
distance ordering (``InfoHash::xorCmp``, include/opendht/infohash.h:179-194).

Design notes (TPU-first):

- 160-bit distances don't fit any native dtype, so ordering is done as a
  **multi-key lexicographic sort over the 5 uint32 distance limbs**
  (``lax.sort(..., num_keys≥5)``), which XLA lowers to a bitonic sorting
  network on TPU — no wide-integer emulation, no data-dependent control
  flow.
- The table is streamed in tiles with ``lax.scan``; a running top-k
  buffer of shape [Q, k, 5] is merged with each tile via one sort of
  [Q, k+T] rows.  Wall-clock is O(N/T · (k+T) log(k+T)) per query batch
  and the working set stays small enough to keep XLA in VMEM-sized
  fusions.
- Ties (duplicate ids in the table) are broken by ascending table index
  — the sort gets the index as a final key, making results fully
  deterministic and making tests exact.
- Invalid rows (tombstones in an append/compact table slab — see
  core/table.py) are excluded with a leading validity key rather than a
  sentinel distance, so *any* real id remains representable.

This full scan is the oracle and the fallback; the fast path for big
tables is the sorted-table window lookup in ops/sorted_table.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from .ids import N_LIMBS, xor_ids

_U32 = jnp.uint32


def gather_rows(table, rows):
    """Reference row-materialization oracle for the fused planar gather
    (``ops.sorted_table.fused_gather_planar``): ``rows`` [...] int32 →
    uint32 [..., 5] table rows, with out-of-range rows (including the
    engine's -1 "absent" sentinel) returned as all-ones — the same
    canonical sentinel :func:`mask_invalid` uses.

    This is the oracle the round-fused reply gather of the iterative
    search engine (core/search.py) is pinned against: the fused gather
    returns *limb planes* and leaves out-of-range lanes as garbage for
    the caller to mask, so the test contract is "masked fused planes ==
    gather_rows limbs" (tests/test_topk.py).  Scan-free and shape-naive
    on purpose — an oracle, not a kernel.
    """
    N = table.shape[0]
    ok = (rows >= 0) & (rows < N)
    g = jnp.take(table, jnp.clip(rows, 0, N - 1).reshape(-1),
                 axis=0).reshape(tuple(rows.shape) + (N_LIMBS,))
    return jnp.where(ok[..., None], g, jnp.uint32(0xFFFFFFFF))


def select_topk(dist, idx, inv, k):
    """Top-k rows of [Q, C] candidates via one lexicographic sort.

    Sort keys, in order: invalid flag (valid first), 5 distance limbs
    (ascending = closest first), then table index (deterministic
    tie-break).  Returns (dist [Q,k,5], idx [Q,k], inv [Q,k]), unmasked —
    apply :func:`mask_invalid` at the output boundary.
    """
    operands = (
        inv,
        dist[..., 0], dist[..., 1], dist[..., 2], dist[..., 3], dist[..., 4],
        idx,
    )
    sorted_ops = lax.sort(operands, dimension=1, num_keys=7)
    new_inv = sorted_ops[0][:, :k]
    new_dist = jnp.stack(sorted_ops[1:6], axis=-1)[:, :k]
    new_idx = sorted_ops[6][:, :k]
    return new_dist, new_idx, new_inv


def mask_invalid(dist, idx, inv):
    """Canonical sentinels on invalid rows: idx → -1, dist → all-ones."""
    idx = jnp.where(inv == 0, idx, -1)
    dist = jnp.where((inv == 0)[..., None], dist,
                     jnp.full_like(dist, 0xFFFFFFFF))
    return dist, idx


def _merge_topk(best_dist, best_idx, best_inv, cand_dist, cand_idx, cand_inv, k):
    """Merge running top-k with tile candidates via one lexicographic sort."""
    dist = jnp.concatenate([best_dist, cand_dist], axis=1)
    idx = jnp.concatenate([best_idx, cand_idx], axis=1)
    inv = jnp.concatenate([best_inv, cand_inv], axis=1)
    return select_topk(dist, idx, inv, k)


@functools.partial(jax.jit, static_argnames=("k", "tile"))
def xor_topk(queries, table, *, k: int = 8, tile: int = 4096, valid=None):
    """Exact k XOR-closest table rows for each query.

    Args:
      queries: uint32 [Q, 5] query ids.
      table:   uint32 [N, 5] node ids (N padded to anything; combine with
               `valid` to exclude padding/tombstones).
      k:       how many closest to return (TARGET_NODES=8 or
               SEARCH_NODES=14 in the reference, routing_table.h:26,
               dht.h:308).
      tile:    table tile size per merge step.
      valid:   optional bool [N]; False rows are never returned.

    Returns:
      dist [Q, k, 5] uint32 XOR distances (all-ones where no valid entry),
      idx  [Q, k] int32 table row indices (-1 where no valid entry).
    """
    Q = queries.shape[0]
    N = table.shape[0]
    if valid is None:
        valid = jnp.ones((N,), dtype=bool)

    # pad table to a multiple of `tile` with invalid rows
    pad = (-N) % tile
    if pad:
        table = jnp.concatenate([table, jnp.zeros((pad, N_LIMBS), _U32)], axis=0)
        valid = jnp.concatenate([valid, jnp.zeros((pad,), bool)], axis=0)
    n_tiles = table.shape[0] // tile

    table_t = table.reshape(n_tiles, tile, N_LIMBS)
    valid_t = valid.reshape(n_tiles, tile)

    init_dist = jnp.full((Q, k, N_LIMBS), 0xFFFFFFFF, dtype=_U32)
    init_idx = jnp.full((Q, k), -1, dtype=jnp.int32)
    init_inv = jnp.ones((Q, k), dtype=jnp.int32)

    def step(carry, inputs):
        best_dist, best_idx, best_inv = carry
        tile_ids, tile_valid, tile_no = inputs
        cand_dist = xor_ids(queries[:, None, :], tile_ids[None, :, :])
        cand_idx = jnp.broadcast_to(
            (tile_no * tile + jnp.arange(tile, dtype=jnp.int32))[None, :], (Q, tile)
        )
        cand_inv = jnp.broadcast_to(
            (~tile_valid).astype(jnp.int32)[None, :], (Q, tile)
        )
        new = _merge_topk(best_dist, best_idx, best_inv,
                          cand_dist, cand_idx, cand_inv, k)
        return new, None

    (best_dist, best_idx, best_inv), _ = lax.scan(
        step,
        (init_dist, init_idx, init_inv),
        (table_t, valid_t, jnp.arange(n_tiles, dtype=jnp.int32)),
    )
    best_dist, best_idx = mask_invalid(best_dist, best_idx, best_inv)
    return best_dist, best_idx


def xor_topk_chunked(queries, table, *, k: int = 8, tile: int = 4096,
                     q_chunk: int = 1024, valid=None):
    """Host-level driver: process queries in chunks to bound memory.
    Returns the same (dist, idx) as :func:`xor_topk`."""
    Q = queries.shape[0]
    outs_d, outs_i = [], []
    for s in range(0, Q, q_chunk):
        d, i = xor_topk(queries[s:s + q_chunk], table, k=k, tile=tile, valid=valid)
        outs_d.append(d)
        outs_i.append(i)
    return jnp.concatenate(outs_d, axis=0), jnp.concatenate(outs_i, axis=0)
