"""Batched listener-table membership match (ISSUE-20).

The reference's proxy layer exists largely to fan stored values out to
subscribers (``DhtProxyServer`` push, ``Dht::storageChanged``), and
until round 24 that fan-out started with a host-side dict probe per
put: every ``storage_store`` walked Python listener records one value
at a time.  This kernel turns the membership question — "which of this
ingest wave's stored-put keys have listeners?" — into ONE XOR-equality
launch over the whole wave, the same Orca-style amortization move the
churn table (PR-7) and the hot-cache probe (PR-11) made: a million
idle-but-subscribed keys cost one batched compare per wave, not a
million dict probes.

Design mirrors :mod:`opendht_tpu.ops.cache_probe` (deliberately — the
all-limb-compare shape is shared):

- ids are the uint32 ``[.., 5]`` limb vectors of :mod:`opendht_tpu.ops.ids`
  — a match is 5 limb compares per (stored key, table slot) pair,
  reduced with ``jnp.all``; match == XOR distance exactly zero.
- the listener table is ``[L, 5]`` with L up to the configured
  capacity (tombstoned rows carry ``valid=False`` and never match —
  the append+tombstone+compact discipline of ``ops/sorted_table.py``'s
  churn path, host-managed in :mod:`opendht_tpu.listeners`).
- a bit-exact numpy mirror (:func:`match_host`) is the tests' oracle
  and the ``listen_batching="off"`` path's membership decision — the
  two delivery paths must reach the SAME hit set (pinned in
  tests/test_listener.py).

The kernel never carries listener records or payloads: per-key listener
sets (local callbacks, remote ``(node, sid)`` sockets, proxy push
subscriptions) live host-side on the :class:`~opendht_tpu.runtime.dht.Dht`
storage, so the device answers membership + slot and the host performs
one coalesced delivery dispatch per wave per listener.  Cost-gated in
perf_budgets.json (``listener_match``) from day one; tp twin
``sharded_listener_match`` in ``parallel/sharded.py``.
"""

from __future__ import annotations

import functools

import numpy as np

from .ids import N_LIMBS

#: default bounded listener table capacity (slots of 20-byte key ids);
#: the [S, L] compare is one fused reduce — at the canonical wave
#: S=64 even L=1e6 is a single ~300M-lane elementwise pass, which is
#: the whole point (the OPEN million-listener bound, perf_budgets.json)
LISTENER_CAPACITY = 1024


@functools.lru_cache(maxsize=8)
def _build_match(capacity: int):
    import jax
    import jax.numpy as jnp

    def fn(table_ids, valid, stored):
        s = stored.reshape(-1, N_LIMBS).astype(jnp.uint32)
        t = table_ids.reshape(-1, N_LIMBS).astype(jnp.uint32)
        # [S, L]: all-limb equality == XOR distance exactly zero;
        # tombstoned/never-filled rows are masked by valid
        eq = jnp.all(s[:, None, :] == t[None, :, :], axis=-1) & valid[None, :]
        hit = jnp.any(eq, axis=1)
        # lowest matching slot (live slots hold distinct ids, so at
        # most one matches; argmax of the mask is deterministic)
        slot = jnp.where(hit, jnp.argmax(eq, axis=1).astype(jnp.int32),
                         jnp.int32(-1))
        return hit, slot
    return jax.jit(fn)


def listener_match(table_ids, valid, stored):
    """ONE batched XOR-equality launch: ``(hit [S] bool, slot [S] int32)``
    for a wave's stored-put keys against the listener table.

    ``table_ids``: uint32 ``[L, 5]`` (device or host), ``valid``: bool
    ``[L]`` (tombstoned rows never match), ``stored``: uint32
    ``[S, 5]``.  ``slot[i]`` is the matching table row, -1 on miss.
    Dispatch is one fused compare-reduce; nothing here blocks until the
    caller reads the result."""
    return _build_match(int(table_ids.shape[0]))(table_ids, valid, stored)


def match_host(table_ids, valid, stored) -> tuple:
    """Bit-exact numpy mirror of :func:`listener_match` — the tests'
    oracle and the ``listen_batching="off"`` path's membership decision
    (the two delivery paths must reach the same hit set)."""
    t = np.asarray(table_ids, np.uint32).reshape(-1, N_LIMBS)
    v = np.asarray(valid, bool).reshape(-1)
    s = np.asarray(stored, np.uint32).reshape(-1, N_LIMBS)
    eq = np.all(s[:, None, :] == t[None, :, :], axis=-1) & v[None, :]
    hit = eq.any(axis=1)
    slot = np.where(hit, eq.argmax(axis=1).astype(np.int32),
                    np.int32(-1))
    return hit, slot
