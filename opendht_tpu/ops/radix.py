"""Radix / k-bucket partition kernels.

The reference's routing table is a list of k-buckets that only ever
splits around the node's own id (src/routing_table.cpp:176-262).  At
steady state that is exactly a partition of peers by their
common-prefix length with the own id: bucket b holds peers whose ids
share the first b bits with self and differ at bit b.  This module
vectorizes that partition and the maintenance sweeps built on it:

- ``bucket_of``       peer → bucket index (= clipped commonBits with self)
- ``bucket_counts``   per-bucket occupancy via a fused [160, N]
                      compare-and-reduce (segment scatters are
                      serialization-bound on TPU — see its docstring)
- ``bucket_last_seen``per-bucket max last-reply time with the reference's
                      never-replied-is-stale semantics (a bucket whose
                      peers never replied reads -inf, ↔ Bucket::time =
                      time_point::min(); bucketMaintenance's 10-min rule,
                      src/dht.cpp:1780-1838) — the single source of truth
                      NodeTable.stale_buckets delegates to
- ``maintenance_sweep`` ONE fused pass: occupancy + staleness + a refresh
                      target per bucket — the round-10 device sweep
                      behind ``Dht::bucketMaintenance``
- ``random_id_in_bucket`` uniform id inside a bucket's range
                      (↔ RoutingTable::randomId, src/routing_table.cpp:67-85)
- ``estimate_network_size`` 8·2^depth (↔ callbacks.h:54)
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .ids import N_LIMBS, ID_BITS, common_bits, set_bit

_U32 = jnp.uint32

MAX_BUCKET = ID_BITS - 1  # deepest distinct bucket (bit 159)


def bucket_of(self_id, ids):
    """Bucket index of each id relative to `self_id`: min(commonBits, 159).

    self_id: uint32 [5]; ids: uint32 [..., 5] → int32 [...].
    The own id (cb=160) lands in bucket 159 with its closest peers.
    """
    cb = common_bits(jnp.broadcast_to(self_id, ids.shape), ids)
    return jnp.minimum(cb, MAX_BUCKET)


@jax.jit
def bucket_counts(self_id, ids, valid):
    """Occupancy of each of the 160 buckets.  int32 [160].

    Computed as a [160, N] compare-and-reduce rather than a
    ``segment_sum``: scatter-adds are serialization-bound on TPU
    (measured 97 ms for 10M unsorted indices vs ~2 ms for this form —
    the compare fuses into the row reduction, and the [160, N]
    orientation keeps the minor dimension unpadded).
    """
    b = bucket_of(self_id, ids)
    bm = jnp.where(valid, b, -1)
    probes = jnp.arange(ID_BITS, dtype=jnp.int32)[:, None]
    return jnp.sum(bm[None, :] == probes, axis=1).astype(jnp.int32)


@jax.jit
def bucket_last_seen(self_id, ids, valid, last_seen):
    """Per-bucket max of `last_seen` (float32/float64 [N]) over valid
    rows THAT EVER REPLIED (``last_seen > 0``).  Buckets with no such
    node get -inf — the reference's never-replied-is-stale rule
    (Bucket::time starts at time_point::min(),
    src/routing_table.cpp:210-211), so a bucket occupied only by
    never-replied peers is stale from birth.  [160].

    Same compare-and-reduce form as :func:`bucket_counts` (a
    ``segment_max`` scatter measured ~45x slower at 10M rows)."""
    b = bucket_of(self_id, ids)
    vals = jnp.where(valid & (last_seen > 0), last_seen, -jnp.inf)
    probes = jnp.arange(ID_BITS, dtype=jnp.int32)[:, None]
    masked = jnp.where(b[None, :] == probes, vals[None, :], -jnp.inf)
    return jnp.max(masked, axis=1)


@jax.jit
def maintenance_sweep(self_id, ids, valid, last_reply, now, age, key):
    """The fused bucket-maintenance pass (round 10): ONE launch over the
    [N, 5] id matrix computing everything ``Dht::bucketMaintenance``
    (src/dht.cpp:1780-1838) needs —

    - ``counts``  int32 [160]   bucket occupancy
    - ``last``    float [160]   per-bucket last reply (-inf when the
                                bucket never heard a reply: never-replied
                                peers are stale from birth)
    - ``stale``   bool  [160]   occupied & silent for ``age`` seconds
                                (the 10-min rule)
    - ``targets`` uint32 [160,5] a uniform refresh id inside EVERY
                                bucket's range (↔ RoutingTable::randomId);
                                the caller selects the stale rows

    The bucket compare ([160, N] broadcast) is computed once and shared
    by the occupancy sum and the staleness max — the same orientation as
    :func:`bucket_counts` (scatter forms measured 45x slower; see its
    docstring).  Targets are generated for all 160 buckets so the output
    shape is static; at [160, 5] the wasted rows are noise next to the
    [160, N] reduction.
    """
    b = bucket_of(self_id, ids)
    bm = jnp.where(valid, b, -1)
    probes = jnp.arange(ID_BITS, dtype=jnp.int32)[:, None]
    hit = bm[None, :] == probes                       # [160, N]
    counts = jnp.sum(hit, axis=1).astype(jnp.int32)
    vals = jnp.where(valid & (last_reply > 0), last_reply, -jnp.inf)
    last = jnp.max(jnp.where(hit, vals[None, :], -jnp.inf), axis=1)
    stale = (counts > 0) & (last < now - age)
    targets = random_id_in_bucket(
        self_id, jnp.arange(ID_BITS, dtype=jnp.int32), key)
    return counts, last, stale, targets


# host-precomputed prefix masks: row b = mask of the first b bits
_PREFIX_MASKS = np.zeros((ID_BITS + 1, N_LIMBS), dtype=np.uint32)
for _b in range(ID_BITS + 1):
    full, rem = divmod(_b, 32)
    _PREFIX_MASKS[_b, :full] = 0xFFFFFFFF
    if rem and full < N_LIMBS:
        _PREFIX_MASKS[_b, full] = (0xFFFFFFFF << (32 - rem)) & 0xFFFFFFFF
del _b


def random_id_in_bucket(self_id, bucket, key):
    """Uniform random id inside bucket `bucket`'s range: shares the first
    `bucket` bits with self, differs at bit `bucket`, random after
    (↔ RoutingTable::randomId, src/routing_table.cpp:67-85).

    bucket: int32 [...]; returns uint32 [..., 5].
    """
    bucket = jnp.asarray(bucket, jnp.int32)
    shape = bucket.shape + (N_LIMBS,)
    rand = jax.random.bits(key, shape, dtype=jnp.uint32)
    masks = jnp.take(jnp.asarray(_PREFIX_MASKS), jnp.clip(bucket, 0, ID_BITS), axis=0)
    out = (jnp.broadcast_to(self_id, shape) & masks) | (rand & ~masks)
    # force the differing bit: flip self's bit at `bucket`
    self_bit = jnp.broadcast_to(
        _bit_at(jnp.broadcast_to(self_id, shape), bucket), bucket.shape
    )
    return set_bit(out, bucket, ~self_bit)


def _bit_at(ids, nbit):
    from .ids import get_bit

    return get_bit(ids, nbit)


def estimate_network_size(self_id, ids, valid, k: int = 8):
    """Network size estimate k·2^depth (↔ NodeStats, callbacks.h:47-67).

    In the reference, table depth is the own-bucket prefix length, which
    grows only while the own bucket keeps k nodes and splits.  Flat-radix
    equivalent: depth = deepest d such that ≥ k valid nodes share a
    ≥ d-bit prefix with self.
    """
    counts = bucket_counts(self_id, ids, valid)
    # nodes with cb >= d, for each d: reverse cumulative sum
    ge = jnp.cumsum(counts[::-1])[::-1]
    depths = jnp.nonzero(ge >= k, size=ID_BITS, fill_value=-1)[0]
    depth = jnp.max(depths)
    return jnp.where(depth < 0, jnp.sum(counts), k * (2 ** jnp.clip(depth, 0, 30)))
