"""Batched hot-cache membership probe (ISSUE-11).

The serving half of the keyspace observatory (``opendht_tpu/hotcache.py``)
keeps a bounded device table of the hot keys' canonical 20-byte ids.
Before an ingest wave launches its ``[Q]`` closest-node resolve
(``runtime/wave_builder.py _launch``), this kernel answers "which of the
wave's targets are cached?" in ONE XOR-compare launch over the whole
wave — a hit peels the carried get off the wave entirely (it is served
from the cache's host-side value payloads), the miss set falls through
to the unchanged lookup launch.

Design mirrors :mod:`opendht_tpu.ops.sketch`:

- ids are the uint32 ``[.., 5]`` limb vectors of :mod:`opendht_tpu.ops.ids`
  — a probe is 5 limb compares per (target, slot) pair, reduced with
  ``jnp.all``; match == XOR distance exactly zero, hence "XOR-compare".
- the cache table is TINY (``[C, 5]`` with C <= a few hundred), so the
  ``[Q, C]`` compare is noise next to the ``[Q, N]`` lookup it spares.
- a bit-exact numpy mirror (:func:`probe_host`) is the tests' oracle,
  and the batching-off escape hatch's per-op membership test — the two
  paths must take the SAME hit/miss decision (pinned in
  tests/test_hotcache.py).

The kernel never carries payloads: values live host-side on the
:class:`~opendht_tpu.hotcache.HotValueCache` keyed by the same canonical
bytes, so the device answers membership + slot and the host serves the
payload.  Cost-gated in perf_budgets.json (``cache_probe``) from day
one; tp twin ``sharded_cache_probe`` in ``parallel/sharded.py``.
"""

from __future__ import annotations

import functools

import numpy as np

from .ids import N_LIMBS

#: default bounded cache table capacity (slots of 20-byte ids); the
#: [Q, C] probe stays tiny against the [Q, N] lookup it replaces
CACHE_CAPACITY = 64


@functools.lru_cache(maxsize=8)
def _build_probe(capacity: int):
    import jax
    import jax.numpy as jnp

    def fn(cache_ids, valid, targets):
        t = targets.reshape(-1, N_LIMBS).astype(jnp.uint32)
        c = cache_ids.reshape(-1, N_LIMBS).astype(jnp.uint32)
        # [Q, C]: all-limb equality == XOR distance exactly zero
        eq = jnp.all(t[:, None, :] == c[None, :, :], axis=-1) & valid[None, :]
        hit = jnp.any(eq, axis=1)
        # lowest matching slot (slots hold distinct ids, so at most one
        # matches; argmax of the mask is deterministic either way)
        slot = jnp.where(hit, jnp.argmax(eq, axis=1).astype(jnp.int32),
                         jnp.int32(-1))
        return hit, slot
    return jax.jit(fn)


def cache_probe(cache_ids, valid, targets):
    """ONE batched XOR-compare launch: ``(hit [Q] bool, slot [Q] int32)``
    for a wave's targets against the cache table.

    ``cache_ids``: uint32 ``[C, 5]`` (device or host), ``valid``: bool
    ``[C]`` (False rows never match), ``targets``: uint32 ``[Q, 5]``.
    ``slot[i]`` is the matching cache row, -1 on miss.  Dispatch is one
    fused compare-reduce; nothing here blocks until the caller reads
    the result."""
    return _build_probe(int(cache_ids.shape[0]))(cache_ids, valid, targets)


def probe_host(cache_ids, valid, targets) -> tuple:
    """Bit-exact numpy mirror of :func:`cache_probe` — the tests'
    oracle and the batching-off path's per-op membership test (the two
    serving paths must take the same decision)."""
    c = np.asarray(cache_ids, np.uint32).reshape(-1, N_LIMBS)
    v = np.asarray(valid, bool).reshape(-1)
    t = np.asarray(targets, np.uint32).reshape(-1, N_LIMBS)
    eq = np.all(t[:, None, :] == c[None, :, :], axis=-1) & v[None, :]
    hit = eq.any(axis=1)
    slot = np.where(hit, eq.argmax(axis=1).astype(np.int32),
                    np.int32(-1))
    return hit, slot
