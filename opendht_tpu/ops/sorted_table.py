"""Sorted-table XOR nearest-neighbor lookup — the fast path.

The reference finds closest nodes two ways: walking k-buckets outward
(src/routing_table.cpp:109-150) or walking a lexicographically-sorted
map outward from ``lower_bound(id)`` picking the XOR-closer side each
step (``NodeCache::getCachedNodes``, src/node_cache.cpp:41-74).  Both
exploit the same property this module vectorizes:

  In lexicographic order, the common-prefix length cp(q, ·) is unimodal
  around q's insertion position, and every node with cp ≥ L forms one
  contiguous run containing that position.  All nodes inside that run
  are XOR-closer to q than any node outside it.

So the k XOR-closest nodes live in a small *window* of the sorted table
around q's position, and we can prove it per query:

  certificate:  cb(q, kth result) > cb(q, nearest excluded neighbor)
                on each side that has excluded nodes.

When the certificate holds (virtually always for random SHA1 ids and
window ≥ 8k), the window result equals the exact full scan; failures
fall back to ops/xor_topk.  This turns the O(Q·N) scan into
O(Q·(log N + W)) — the difference between 1M×10M = 10^13 limb ops and
~1M×300 = 3·10^8, which is what makes the BASELINE.json north star
(<1 ms amortized per lookup) reachable.

All steps are static-shape, batched, and jit/shard_map friendly:
binary search is a fixed ``ceil(log2 N)``-step ``fori_loop``; the window
merge is one 7-key lexicographic sort (see ops/xor_topk.py for the key
layout) or the pallas selection kernel (ops/pallas_select.py).

Negative result (recorded so it isn't retried): fusing the window
*gather* into a pallas kernel — DMAing each query's window straight
from the HBM-resident table via scalar-prefetched start offsets — does
not work on TPU.  Mosaic requires slice offsets aligned to the memref
tiling (1024 elements for 1-D int32, 8 sublanes for 2-D), so arbitrary
per-query window starts either fail to compile or force the window to
be widened ~8× to the alignment grid, destroying the HBM-traffic
saving that motivated the fusion.  XLA's general gather handles the
unaligned access pattern natively; the win that *was* available —
replacing the post-gather sort with VPU min-extraction — is
ops/pallas_select.py.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from .. import telemetry
from .ids import N_LIMBS, xor_ids, common_bits, clz32
from .xor_topk import xor_topk

_U32 = jnp.uint32


@functools.partial(jax.jit, static_argnames=())
def sort_table(ids, valid=None):
    """Sort id rows lexicographically; invalid rows sink to the end.

    Returns (sorted_ids [N,5], perm [N] int32 original row of each sorted
    row, n_valid int32).  ``perm`` is -1 on rows that were invalid.
    """
    N = ids.shape[0]
    if valid is None:
        valid = jnp.ones((N,), dtype=bool)
    inv = (~valid).astype(jnp.int32)
    idx = jnp.arange(N, dtype=jnp.int32)
    ops_in = (inv, ids[:, 0], ids[:, 1], ids[:, 2], ids[:, 3], ids[:, 4], idx)
    out = lax.sort(ops_in, dimension=0, num_keys=6)
    sorted_ids = jnp.stack(out[1:6], axis=-1)
    perm = jnp.where(out[0] == 0, out[6], -1)
    n_valid = jnp.sum(valid.astype(jnp.int32))
    return sorted_ids, perm, n_valid


LUT_BITS = 16


def default_lut_bits(n_rows: int) -> int:
    """Prefix width for :func:`build_prefix_lut` sized to the table:
    ~1-row buckets (bits ≈ log2 N), clamped to [16, 24].  Keeping the
    average bucket ≈ 1 row is what makes the LUT-only (0-step)
    positioning mode safe: positioning error is bounded by bucket size,
    and the expanded window's stride-wide margin absorbs it (a 64M-row
    table at 20 bits has ~61-row average buckets — comparable to the
    margin itself — while 24 bits brings them to ~4).  The 24-bit cap
    costs a 64 MiB LUT — noise next to the expanded table."""
    return min(24, max(16, math.ceil(math.log2(max(n_rows, 2)))))
# binary-search depth inside one LUT bucket: buckets of a 2^16-way
# partition of N uniform ids are ~N/2^16 rows; 4096 (2^12) is a huge
# overshoot for any realistic N, and an adversarial bucket larger than
# that merely yields a wrong window that the exactness certificate
# catches (→ full-scan fallback).  Measured on v5e-lite @ N=1M the LUT
# path is within noise of the plain 21-step search (the per-step gather
# fuses well), so it stays opt-in — it pays when N grows enough that
# log2(N) - LUT_BUCKET_STEPS widens.
LUT_BUCKET_STEPS = 13


@functools.partial(jax.jit, static_argnames=("bits",))
def build_prefix_lut(sorted_ids, n_valid, *, bits: int = LUT_BITS):
    """Top-``bits`` prefix → first sorted row with that prefix or greater.

    Shrinks the per-query binary search from ceil(log2 N)+1 sequential
    gather steps to a handful of in-bucket steps, which is where a third
    of the lookup wall-clock goes at N=1M.  Invalid rows (sorted to the
    end) get the sentinel prefix 2^bits so every real prefix resolves
    below n_valid.  Returns int32 [2^bits + 1]; entry [p+1] bounds
    bucket p.  ``bits`` is recoverable from the result shape, so
    consumers infer it — size it with :func:`default_lut_bits`
    (~1-row buckets at any N, which is what keeps the LUT-only 0-step
    positioning mode inside the expanded window's margin).
    """
    N = sorted_ids.shape[0]
    nb = 1 << bits
    keys = (sorted_ids[:, 0] >> jnp.uint32(32 - bits)).astype(jnp.int32)
    keys = jnp.where(jnp.arange(N) < jnp.asarray(n_valid, jnp.int32),
                     keys, jnp.int32(nb))
    # histogram + exclusive cumsum, NOT searchsorted: on sorted keys
    # "first row with prefix >= p" is exactly sum(counts[< p]), and the
    # scatter-add + scan build is one pass over N + one over 2^bits —
    # measured ~8 ms faster per build at 2^18 probes on v5e, which is
    # what makes the churn path's per-round delta LUT rebuild free
    # (benchmarks/baseline_configs.py config6).
    counts = jnp.zeros((nb + 1,), jnp.int32).at[keys].add(1)
    return jnp.concatenate([jnp.zeros((1,), jnp.int32),
                            jnp.cumsum(counts[:nb], dtype=jnp.int32)])


def _lut_bits(lut) -> int:
    """Recover the prefix width from a build_prefix_lut result shape."""
    return (lut.shape[0] - 1).bit_length() - 1


def lut_budget_steps(n_rows: int, bits: int) -> int:
    """In-bucket binary-search depth used when ``lut_steps=None``:
    covers buckets up to 64× the expected N/2^bits size.  THE single
    definition — the soundness guard in core/search.py
    (``_guarded_lower_bound``) certifies the LUT path against exactly
    this budget, so the two must never diverge."""
    return max(6, math.ceil(math.log2(max(n_rows, 2))) - bits + 6)


def fused_gather_planar(sorted_t, rows, limbs: int = N_LIMBS):
    """ONE fused multi-row gather: ``limbs`` limb planes of arbitrary-
    shaped row indices out of the TRANSPOSED [5, N] table.

    THE table-access primitive of the iterative search round
    (core/search.py): the round body packs every row it needs — all
    α·k reply rows of every search in the wave — into a single flat
    index vector, so the device issues exactly one gather per round
    instead of one per candidate set (per-element gathers are
    issue-bound at ~190K rows/ms on v5e; what matters is the *number
    of gather ops on the serial chain*, not their element count, once
    waves are small).  The transposed-table / planar-output form is the
    lane-padding rule from the layout note in
    :func:`~opendht_tpu.core.search.simulate_lookups`: a [M, 5] row
    gather pads its minor dim 5 → 128 in TPU tiled layout; [5, M]
    planes stay unpadded.

    Exact by construction and pinned against the full-materialization
    oracle :func:`~opendht_tpu.ops.xor_topk.gather_rows`
    (tests/test_topk.py).  Out-of-range rows (e.g. the engine's -1
    "absent" sentinel) are clipped, so their lanes carry garbage —
    every caller masks them (the oracle returns the all-ones sentinel
    there instead).
    """
    N = sorted_t.shape[1]
    cl = jnp.clip(rows, 0, N - 1).reshape(-1)
    g = jnp.take(sorted_t[:limbs], cl, axis=1)          # [limbs, M]
    return [g[l].reshape(rows.shape) for l in range(limbs)]


def _lex_lt(g, q_l, limbs: int):
    """Planar lexicographic row < query over ``limbs`` uint32 planes:
    ``g`` [limbs, M] gathered rows, ``q_l`` list of [M] query limbs.
    THE single definition — used by the binary-search probe step here
    and by the exact-correction step in core/search.py."""
    lt = g[limbs - 1] < q_l[limbs - 1]
    for l in range(limbs - 2, -1, -1):
        lt = (g[l] < q_l[l]) | ((g[l] == q_l[l]) & lt)
    return lt


def _lower_bound(sorted_ids, queries, n_valid, lut=None,
                 lut_steps: int = LUT_BUCKET_STEPS,
                 limbs: int = N_LIMBS):
    """First index i in [0, n_valid] with sorted_ids[i] >= q, batched.

    Fixed-depth binary search (static ceil(log2 N)+1 steps) — no
    data-dependent control flow, so it stays one fused XLA loop.  With a
    prefix ``lut`` (build_prefix_lut) the search starts inside the
    query's 2^16-way bucket and needs only LUT_BUCKET_STEPS steps.

    ``limbs`` restricts the comparison to the top ``limbs`` uint32
    limbs (the probe-step gather is the dominant cost — it is
    per-element issue-bound, so 2 limbs cost 2/5 of 5).  The result is
    then the lower bound in the TRUNCATED key order; see
    core/search.py ``_guarded_lower_bound`` for the exact-correction
    construction (truncated search + one full-width compare step).
    """
    N = sorted_ids.shape[0]
    Q = queries.shape[0]
    if lut is not None:
        bits = _lut_bits(lut)
        p = (queries[:, 0] >> jnp.uint32(32 - bits)).astype(jnp.int32)
        lo = jnp.take(lut, p)
        hi = jnp.take(lut, p + 1)
        if lut_steps is None:
            # larger (adversarial) buckets merely fail the certificate
            lut_steps = lut_budget_steps(N, bits)
        steps = lut_steps
    else:
        steps = max(1, math.ceil(math.log2(max(N, 2))) + 1)
        lo = jnp.zeros((Q,), jnp.int32)
        hi = jnp.broadcast_to(jnp.asarray(n_valid, jnp.int32), (Q,))

    # gather probe rows limb-planar from the transposed table: a [Q, 5]
    # row gather pads 5 lanes → 128 in TPU tiled layout; [5, Q] columns
    # stay unpadded and the lex compare runs on 1-D planes
    sorted_t = sorted_ids.T[:limbs]                          # [limbs, N]
    q_l = [queries[:, l] for l in range(limbs)]

    def body(_, lohi):
        lo, hi = lohi
        mid = (lo + hi) // 2
        g = jnp.take(sorted_t, jnp.clip(mid, 0, N - 1), axis=1)  # [limbs, Q]
        go_right = _lex_lt(g, q_l, limbs) & (lo < hi)
        new_lo = jnp.where(go_right, mid + 1, lo)
        new_hi = jnp.where(go_right | (lo >= hi), hi, mid)
        return new_lo, new_hi

    lo, hi = lax.fori_loop(0, steps, body, (lo, hi))
    return lo


@functools.partial(jax.jit,
                   static_argnames=("k", "window", "select", "lut_steps"))
def window_topk(sorted_ids, n_valid, queries, *, k: int = 8, window: int = 128,
                select: str = "auto", lut=None,
                lut_steps: int = LUT_BUCKET_STEPS):
    """k XOR-closest among the first n_valid rows of a sorted table,
    searched only within a `window`-wide slice around each query's
    sorted position, plus a per-query exactness certificate.

    ``select`` picks the in-window top-k engine: ``"sort"`` = 7-key
    ``lax.sort``; ``"pallas"`` = the VPU min-extraction kernel
    (ops/pallas_select.py); ``"auto"`` = pallas on TPU, sort elsewhere.
    Both are exact and bit-identical (tests/test_topk.py).  ``lut`` is
    an optional prefix table from :func:`build_prefix_lut` that
    shortens the positioning search; a misplaced window from an
    overflowing LUT bucket is caught by the certificate.

    Returns:
      dist      [Q, k, 5] uint32 (all-ones beyond n_valid results)
      idx       [Q, k] int32 indices into the *sorted* table (-1 = none)
      certified [Q] bool — True ⇒ provably equal to the exact full scan
    """
    if window < k:
        raise ValueError(f"window ({window}) must be >= k ({k})")
    if select == "auto":
        select = "pallas" if jax.default_backend() == "tpu" else "sort"
    N = sorted_ids.shape[0]
    Q = queries.shape[0]
    n_valid = jnp.asarray(n_valid, jnp.int32)

    pos = _lower_bound(sorted_ids, queries, n_valid, lut=lut,
                       lut_steps=lut_steps)

    # slide the window to stay inside [0, n_valid) as much as possible
    start = jnp.clip(pos - window // 2, 0, jnp.maximum(n_valid - window, 0))
    offs = jnp.arange(window, dtype=jnp.int32)
    raw = start[:, None] + offs[None, :]                     # [Q, W]
    inv = (raw >= n_valid).astype(jnp.int32)
    gidx = jnp.clip(raw, 0, N - 1)
    win_ids = jnp.take(sorted_ids, gidx.reshape(-1), axis=0).reshape(Q, window, N_LIMBS)

    dist = xor_ids(queries[:, None, :], win_ids)
    if select == "pallas":
        from .pallas_select import lex_topk_select
        sel = lex_topk_select(dist, inv, k=k,
                              interpret=jax.default_backend() != "tpu")
        found = sel >= 0
        selc = jnp.clip(sel, 0, window - 1)
        top_inv = (~found).astype(jnp.int32)
        top_idx = jnp.where(found, jnp.take_along_axis(raw, selc, axis=1), -1)
        top_dist = jnp.where(
            found[..., None],
            jnp.take_along_axis(dist, selc[..., None], axis=1),
            jnp.uint32(0xFFFFFFFF))
    else:
        ops_in = (
            inv,
            dist[..., 0], dist[..., 1], dist[..., 2], dist[..., 3],
            dist[..., 4],
            raw,
        )
        out = lax.sort(ops_in, dimension=1, num_keys=7)
        top_inv = out[0][:, :k]
        top_dist = jnp.stack(out[1:6], axis=-1)[:, :k]
        top_idx = jnp.where(top_inv == 0, out[6][:, :k], -1)
        top_dist = jnp.where((top_inv == 0)[..., None], top_dist,
                             jnp.full_like(top_dist, 0xFFFFFFFF))

    left_ids = jnp.take(sorted_ids, jnp.clip(start - 1, 0, N - 1), axis=0)
    right_ids = jnp.take(sorted_ids, jnp.clip(start + window, 0, N - 1), axis=0)
    # recover the kth id from its distance (id = q ^ dist)
    kth_ids = xor_ids(queries, top_dist[:, k - 1])
    certified = _window_certificate(
        queries, common_bits(queries, kth_ids), top_inv[:, k - 1] == 0,
        left_ids, right_ids, start > 0, (start + window) < n_valid)
    return top_dist, top_idx, certified


def _cb_clamped(queries, ids):
    """Common-prefix bits of ``queries`` [Q,5] vs ``ids`` [Q,L], clamped
    at 32·L when only the top L limbs are available.  Equal to
    ops.ids.common_bits for L=5."""
    L = ids.shape[-1]
    out = jnp.full(queries.shape[:-1], 32 * L, dtype=jnp.int32)
    prev_zero = jnp.ones(queries.shape[:-1], dtype=bool)
    for l in range(L):
        xi = queries[..., l] ^ ids[..., l]
        first = prev_zero & (xi != 0)
        out = jnp.where(first, 32 * l + clz32(xi), out)
        prev_zero = prev_zero & (xi == 0)
    return out


def _window_certificate(queries, cp_k, kth_valid, left_ids, right_ids,
                        left_exists, right_exists):
    """Exactness certificate shared by the window and expanded lookups.

    Nodes excluded on the left are all at sorted index < start; the
    closest-in-order one is start-1 and (prefix monotonicity) carries the
    maximal common prefix cbL among them.  Any excluded node's distance
    is >= 2^(159-cbL), while the kth window result's distance is
    < 2^(160-cp_k); cp_k > cbL makes every window top-k strictly closer
    than every excluded node.  Symmetrically on the right.  ``cp_k`` may
    be a lower bound — that only makes the certificate conservative.

    With 2-limb neighbor ids (the 2-plane fast2 expansion) cbL/cbR clamp
    at 64; since the fast2 ``cp_k`` is itself clamped at 64, the
    comparison ``cp_k > cb`` is unchanged: a true cb ≥ 64 denies the
    certificate either way (cp_k ≤ 64 can never exceed it), and below
    64 the clamped value is exact — so the 2-plane certificate is
    bit-identical to the 5-plane fast2 one (tests/test_topk.py).
    """
    cbL = _cb_clamped(queries, left_ids)
    cbR = _cb_clamped(queries, right_ids)
    covers_all = (~left_exists) & (~right_exists)
    ok_left = (~left_exists) | (cp_k > cbL)
    ok_right = (~right_exists) | (cp_k > cbR)
    return covers_all | (kth_valid & ok_left & ok_right)


# ---------------------------------------------------------------------------
# Expanded-table path: window fetch as ONE row gather.
#
# Measured on the real chip (v5e), XLA lowers the [Q·W]-element window
# gather of window_topk to a per-element gather running at ~190K rows/ms
# (~4 GB/s — 200× under HBM bandwidth), which is >80% of lookup
# wall-clock at Q=131072, N=1M.  Row gathers with wide contiguous rows,
# by contrast, run near memory speed ([131072, 128] uint32 rows in
# ~0.5 ms).  So we trade 3× table memory for gather shape: the sorted
# table is pre-expanded into overlapping window *rows*
#
#   expanded[j] = sorted_ids[64·j : 64·j + 192]        (stride 64, len 192)
#
# built with reshape+concat only (no gather).  Any 128-wide window
# [pos-64, pos+64) is contained in row j = floor((pos-64)/64), so one
# [Q]-index row gather fetches every query's full candidate set; near
# the table end j is clamped so the window's valid part reaches
# n_valid, mirroring window_topk's slide.  The same exactness
# certificate applies with window start 64·j.
# ---------------------------------------------------------------------------

EXPAND_STRIDE = 64
EXPAND_LEN = 3 * EXPAND_STRIDE          # candidate window rows per entry
_EROW = EXPAND_LEN + 2                  # + left/right certificate neighbors

# Strides an expansion may be built with.  A closed set on purpose: the
# consumer (:func:`expanded_topk`) infers (erow, stride) from
# width // planes, and a MIS-DECLARED ``planes`` can alias
# arithmetically — e.g. a 5-plane stride-64 row (970 lanes) read as
# planes=2 parses to a "valid-looking" erow=485 / stride=161 and
# produces silently wrong, certificate-passing windows (ADVICE r5
# finding 1).  No supported stride is reachable by any cross-planes
# misparse of another supported stride (asserted in tests/test_topk.py),
# so validating the inferred stride against this set turns the silent
# corruption into a loud ValueError.  Extend the set when sweeping new
# geometries — membership is the only constraint.
SUPPORTED_STRIDES = frozenset({8, 16, 24, 32, 42, 48, 64, 96, 128})


@functools.partial(jax.jit, static_argnames=("stride", "limbs"))
def expand_table(sorted_ids, *, stride: int = EXPAND_STRIDE,
                 limbs: int = N_LIMBS):
    """[N, 5] sorted ids → [ceil(N/s), limbs·(3s+2)] overlapping window
    rows (s = ``stride``; default 64 → 194-lane planes).

    ``limbs`` < 5 builds only the top limb planes — the **2-plane form**
    is sufficient for the ``select="fast2"`` lookup (nodes-not-distances
    contract): the fast2 sort consumes planes 0-1 only, and its
    exactness certificate clamps the kth result's common prefix at 64
    bits (:func:`expanded_topk`), so the neighbor-lane comparison needs
    the same two planes.  That cuts the dominant per-query row-gather
    traffic by 3/5 and the expansion memory from 3× to 1.2× of the
    table (the round-4 verdict's ask #2).

    Row j holds sorted rows [s·j-1, s·j+3s+1) in **limb-planar** order:
    lanes [l·(3s+2), (l+1)·(3s+2)) are limb l of those 3s+2 rows.
    Within each plane, lane 0 is the *left certificate neighbor* (row
    s·j-1; zeros sentinel for j=0), lanes 1..3s the candidate window
    [s·j, s·j+3s), lane 3s+1 the *right certificate neighbor* — so one
    row gather fetches both the full candidate set and the rows the
    exactness certificate compares against.  Window length is fixed at
    3·stride: the middle third is the positioning target, leaving a
    ``stride``-row margin on each side (which is also the tolerance the
    LUT-only zero-step positioning mode relies on — see
    :func:`expanded_topk`).

    Limb-planar layout matters: a [Q, W, 5] candidate tensor pads its
    minor dim 5 → 128 lanes in TPU tiled layout (25× physical memory,
    measured ~13 GB of traffic per 131K-query batch).  Keeping each
    limb a contiguous lane slice of a 2-D row keeps every downstream
    op 2-D and unpadded.  Rows past the end are zero-padded (excluded
    at lookup time via n_valid masking).  Pure pad/reshape/concat — no
    gather.  Memory is 3× the table at any stride; halving the stride
    halves the per-query gather traffic and the in-window sort width.
    ``stride`` must be registered in :data:`SUPPORTED_STRIDES` — the
    closed set is what lets :func:`expanded_topk` reject a mis-declared
    ``planes`` loudly instead of misparsing the row geometry.
    """
    if stride not in SUPPORTED_STRIDES:
        raise ValueError(f"stride {stride} not in SUPPORTED_STRIDES "
                         f"{sorted(SUPPORTED_STRIDES)} — register new "
                         "sweep geometries there")
    N = sorted_ids.shape[0]
    NB = -(-N // stride)
    nblk = NB + 4
    pad = nblk * stride - N - 1
    padded = jnp.pad(sorted_ids, ((1, pad), (0, 0)))    # padded[i] = sorted[i-1]
    planes = []
    for l in range(limbs):
        Bl = padded[:, l].reshape(nblk, stride)
        planes.append(jnp.concatenate(
            [Bl[:NB], Bl[1:NB + 1], Bl[2:NB + 2], Bl[3:NB + 3, :2]], axis=1))
    return jnp.concatenate(planes, axis=1)


def expand_table_chunked(sorted_ids, *, stride: int = EXPAND_STRIDE,
                         chunks: int = 8, limbs: int = N_LIMBS):
    """Same window-row table as :func:`expand_table`, built in
    ``chunks`` pieces with a donated in-place row update.

    :func:`expand_table`'s one-shot build peaks at ~2.5× the output
    size (padded copy + per-limb planes + the concatenated result live
    together), which OOMs a 64M-id table (3.9 GB output) on this
    chip's effective HBM.  Here each piece covers NB/chunks output
    rows (one gather from the sorted table with sentinel masking at
    the edges), and ``lax.dynamic_update_slice`` with a donated
    destination keeps exactly one output-sized buffer alive — peak =
    output + input + one piece.

    The result may carry a few zero-padded trailing rows (NB rounded
    up to a multiple of ``chunks``); lookups never touch them (the
    ``jmax`` clamp in :func:`expanded_topk` is bounded by ``n_valid``).
    Bit-identical to ``expand_table`` on the common rows
    (tests/test_topk.py).
    """
    if stride not in SUPPORTED_STRIDES:
        raise ValueError(f"stride {stride} not in SUPPORTED_STRIDES "
                         f"{sorted(SUPPORTED_STRIDES)} — register new "
                         "sweep geometries there")
    N = sorted_ids.shape[0]
    NB = -(-N // stride)
    NBc = -(-NB // chunks)
    erow = 3 * stride + 2
    src_rows = (NBc + 3) * stride          # per-piece source span

    @jax.jit
    def build_piece(sorted_ids, start):
        # rows [start, start+src_rows) of the sentinel-padded table
        # (padded[i] = sorted[i-1]); out-of-range rows are zeros
        idx = start + jnp.arange(src_rows, dtype=jnp.int32) - 1
        ok = (idx >= 0) & (idx < N)
        src = jnp.where(ok[:, None],
                        jnp.take(sorted_ids, jnp.clip(idx, 0, N - 1),
                                 axis=0), jnp.uint32(0))
        planes = []
        for l in range(limbs):
            Bl = src[:, l].reshape(NBc + 3, stride)
            planes.append(jnp.concatenate(
                [Bl[:NBc], Bl[1:NBc + 1], Bl[2:NBc + 2], Bl[3:NBc + 3, :2]],
                axis=1))
        return jnp.concatenate(planes, axis=1)          # [NBc, limbs·erow]

    @functools.partial(jax.jit, donate_argnums=(0,))
    def upd(out, piece, row0):
        return lax.dynamic_update_slice(out, piece, (row0, jnp.int32(0)))

    out = jnp.zeros((chunks * NBc, limbs * erow), jnp.uint32)
    for c in range(chunks):
        piece = build_piece(sorted_ids, jnp.int32(c * NBc * stride))
        out = upd(out, piece, jnp.int32(c * NBc))
    return out


def unpack_tomb_bits(tomb_bits, n: int):
    """Packed little-endian uint32 tombstone words → bool [n] mask.
    Word w bit b covers sorted position 32·w + b (the packing
    :func:`churn_lookup_topk` and core/table.py agree on)."""
    nw = tomb_bits.shape[0]
    words = jnp.repeat(tomb_bits, 32)[:n]
    shifts = jnp.tile(jnp.arange(32, dtype=jnp.uint32), nw)[:n]
    return ((words >> shifts) & 1) != 0


@functools.partial(jax.jit, static_argnames=("k", "select", "lut_steps",
                                             "fast2_limbs", "planes"))
def expanded_topk(sorted_ids, expanded, n_valid, queries, *, k: int = 8,
                  select: str = "auto", lut=None, lut_steps=None,
                  tomb_bits=None, fast2_limbs: bool = False,
                  planes: int = N_LIMBS):
    """k XOR-closest via the expanded table — one row gather per query.

    ``planes`` declares how many limb planes ``expanded`` carries
    (``expand_table(..., limbs=planes)``).  ``planes=2`` is valid only
    with ``select="fast2"`` — the sort and the (clamped) certificate
    consume planes 0-1 only, so the gathered row shrinks 5→2 planes
    (the dominant HBM traffic of the headline kernel; results are
    bit-identical to the 5-plane fast2 path).

    ``select``: ``"pallas"`` = fused min-extraction kernel
    (ops/pallas_window_topk.py — exact 5-limb ordering, but measured
    slower than the sorts on v5e; see below); ``"sort"`` = full 7-key
    lexicographic sort (always exact
    in-window); ``"fast3"`` = 3-key comparator (invalid, d0, d1) with
    limbs 2-4 riding as payload — exact unless two candidates tie on
    the top 64 distance bits (≈2^-47 per pair; detected by an
    adjacent-tie check over the first k+1 sorted rows and folded into
    ``certified``, so ties fall back like any uncertified query).
    ``"fast2"`` = like fast3 but limbs 2-4 are not carried at all and
    the invalid flag is folded into sentinel key values — the sort
    moves 3 operands instead of 7 (sort cost is linear in operand
    count; measured 7.5 ms for the 4-operand form vs 14.8 ms for 7 per
    131K×192 batch on v5e) and ``dist`` comes back as ``None``.  The
    certificate then uses a
    *lower bound* on the kth result's common prefix (exact below 64
    bits, clamped at 64 above — conservative, so borderline queries
    decertify rather than mis-certify).  Use it when the caller needs
    nodes, not distances — the reference's ``findClosestNodes``
    contract (src/routing_table.cpp:109-150).
    ``"auto"`` = fast3 everywhere — measured on v5e, the XLA bitonic
    sort beats the pallas min-extraction kernel (17.7 ms vs ~78 ms per
    131K×192 batch; Mosaic cross-lane reductions cost ~1000 cycles
    each, and the kernel needs 6 per extraction round), so the pallas
    path stays opt-in as a recorded negative result.

    Returns (dist [Q,k,5] — ``None`` for fast2, idx [Q,k] sorted-table
    rows, certified [Q]) with the same contract as :func:`window_topk`.
    """
    if select == "auto":
        select = "fast3"
    if planes != N_LIMBS and select != "fast2":
        raise ValueError(f"planes={planes} requires select='fast2' "
                         f"(got {select!r}) — only the fast2 sort and "
                         "certificate are sound on partial limb planes")
    if planes < 2:
        raise ValueError("planes must be >= 2 (fast2 sorts on d0, d1)")
    if expanded.shape[1] % planes:
        # catches the easy mismatch now that 2- and 5-plane expansions
        # coexist for one table (e.g. a 2-plane stride-64 row is 388
        # lanes — not divisible by the default planes=5).
        raise ValueError(
            f"expanded width {expanded.shape[1]} is not a multiple of "
            f"planes={planes} — pass the planes= the expansion was "
            "built with (expand_table limbs=)")
    NB = expanded.shape[0]
    erow = expanded.shape[1] // planes      # lanes per limb plane = 3s+2
    wlen = erow - 2                         # candidate window rows = 3s
    stride = wlen // 3
    if wlen != 3 * stride or stride not in SUPPORTED_STRIDES:
        # the divisibility check above cannot catch every mis-declared
        # `planes` (a 5-plane stride-64 row is 970 lanes — divisible by
        # 2 — and would silently misparse to stride 161); no supported
        # stride is reachable by a cross-planes misparse of another, so
        # this turns silently-wrong certified windows into a loud error
        # (ADVICE r5 finding 1).
        raise ValueError(
            f"expanded width {expanded.shape[1]} with planes={planes} "
            f"infers stride {wlen / 3:g} not in SUPPORTED_STRIDES "
            f"{sorted(SUPPORTED_STRIDES)} — `planes` does not match the "
            "expand_table(limbs=) the expansion was built with, or the "
            "stride is unregistered")
    n_valid = jnp.asarray(n_valid, jnp.int32)

    pos = _lower_bound(sorted_ids, queries, n_valid, lut=lut,
                       lut_steps=lut_steps)
    # slide at the table end like window_topk: clamp j so the window's
    # valid part always reaches n_valid (jmax start + 3s ≥ n_valid, at
    # most s-1 masked lanes at the top).  Without this clamp, queries in
    # the last ~2s rows keep a one-sided window and decertify — which
    # is sound but needlessly falls back (and in the sharded path flips
    # the whole-shard exact-scan cond).
    jmax = jnp.clip(-((wlen - n_valid) // stride), 0, NB - 1)
    j = jnp.clip((pos - stride) // stride, 0, jmax)
    start = j * stride

    # Tombstones (churn path, core/table.py): a packed bitmask over
    # *sorted positions* folds dead rows into the in-window invalid
    # lanes, so evictions need no re-sort.  stride % 32 == 0 keeps the
    # extraction gather-free: window starts land on word boundaries, so
    # each query reads wlen/32 whole words (one tiny [Q, nw] gather) and
    # the per-lane bit is static (lane L → word L//32, bit L%32 — a
    # repeat/tile, not a gather).  The exactness certificate is
    # unaffected: it bounds rows *outside* the window via the edge
    # neighbors' sorted-order position, which liveness doesn't change,
    # and dead in-window rows are merely unselectable.
    tomb = None
    if tomb_bits is not None:
        if stride % 32:
            raise ValueError(
                f"tomb_bits requires stride % 32 == 0 (got {stride})")
        Q = queries.shape[0]
        sw = stride // 32
        nw = wlen // 32                         # = 3·sw
        # Block the word array into per-window ROWS (same shifted-slice
        # trick as expand_table) so the per-query fetch is one row
        # gather — a flat [Q·nw] element gather is issue-rate-bound and
        # measured ~7 ms/131K-batch; the [NB, nw] build is one pass
        # over the (tiny) word array, fused into the same program.
        padw = (NB + 2) * sw - tomb_bits.shape[0]
        Bw = jnp.pad(tomb_bits, (0, max(padw, 0)))[:(NB + 2) * sw] \
            .reshape(NB + 2, sw)
        tomb_rows = jnp.concatenate([Bw[:NB], Bw[1:NB + 1], Bw[2:NB + 2]],
                                    axis=1)     # [NB, nw]
        words = jnp.take(tomb_rows, j, axis=0)  # [Q, nw] row gather
        shifts = jnp.tile(jnp.arange(32, dtype=jnp.uint32), nw)
        tomb = ((jnp.repeat(words, 32, axis=1) >> shifts[None, :]) & 1) != 0

    rows = jnp.take(expanded, j, axis=0)             # [Q, planes·(3s+2)]
    # limb planes — contiguous lane slices, everything stays 2-D
    plane = [rows[:, l * erow:(l + 1) * erow] for l in range(planes)]
    left_ids = jnp.stack([p[:, 0] for p in plane], axis=-1)
    right_ids = jnp.stack([p[:, erow - 1] for p in plane], axis=-1)

    if select == "pallas":
        from .pallas_window_topk import window_select
        if tomb is not None:
            raise ValueError("tomb_bits is not supported by the pallas "
                             "select (bounds-based masking only)")
        if erow != _EROW:
            raise ValueError("pallas window_select supports only the "
                             f"default stride {EXPAND_STRIDE}")
        Q = queries.shape[0]
        q8 = jnp.pad(queries, ((0, 0), (0, 8 - N_LIMBS)))
        bounds = jnp.broadcast_to(
            jnp.clip(n_valid - start, 0, wlen)[:, None], (Q, 8)
        ).astype(jnp.int32)
        packed = window_select(rows, q8, bounds, k=k,
                               interpret=jax.default_backend() != "tpu")
        local = packed[:, N_LIMBS * k:(N_LIMBS + 1) * k].astype(jnp.int32)
        gidx = start[:, None] + local
        valid_k = (local < wlen) & (gidx < n_valid)
        top_limbs = [jnp.where(valid_k, packed[:, l * k:(l + 1) * k],
                               jnp.uint32(0xFFFFFFFF))
                     for l in range(N_LIMBS)]
        top_idx = jnp.where(valid_k, gidx, -1)
        top_dist = jnp.stack(top_limbs, axis=-1)           # single 3-D build
    elif select == "fast2":
        # 3-OPERAND sort: the invalid flag is folded into sentinel
        # values — invalid lanes get (d0, d1, gr) = (~0, ~0, GR_SENT),
        # which sorts after every valid candidate (a genuine candidate
        # with an all-ones top-64 distance still wins the gr tiebreak,
        # and its cp_k lower bound is 0, so the certificate can never
        # certify that query — the ambiguity is unreachable in
        # certified output).  Sort cost is linear in operand count:
        # 4 → 3 operands is 25% off the headline kernel's largest term.
        big = jnp.uint32(0xFFFFFFFF)
        gr = start[:, None] + jnp.arange(wlen, dtype=jnp.int32)[None, :]
        inv_m = gr >= n_valid
        if tomb is not None:
            inv_m = inv_m | tomb
        gr_sent = jnp.int32(0x7FFFFFFF)
        d0 = jnp.where(inv_m, big, plane[0][:, 1:erow - 1]
                       ^ queries[:, 0:1])
        d1 = jnp.where(inv_m, big, plane[1][:, 1:erow - 1]
                       ^ queries[:, 1:2])
        grm = jnp.where(inv_m, gr_sent, gr)
        out = lax.sort((d0, d1, grm), dimension=1, num_keys=3)
        valid_k = out[2][:, :k] != gr_sent
        top_limbs = [jnp.where(valid_k, out[l][:, :k], big)
                     for l in range(2)]
        top_idx = jnp.where(valid_k, out[2][:, :k], -1)
        # fast2_limbs: hand the sorted top-64 distance bits to the
        # caller as a TUPLE of 2-D [Q, k] planes (churn_lookup_topk
        # merges on them without re-gathering ids).  Planes, not a
        # [Q, k, 2] stack: a minor dim of 2 pads to 128 lanes in TPU
        # tiled layout — the stacked form materialized 64× the bytes
        # and showed up as ~5 ms of unattributed churn-round cost
        # (benchmarks/exp_churn2_r5.py).
        top_dist = (tuple(top_limbs) if fast2_limbs else None)
        # tie-check operands (same layout as the keyed form below)
        tie_a0, tie_a1 = out[0][:, :k + 1], out[1][:, :k + 1]
        tie_av = out[2][:, :k + 1] != gr_sent
    else:
        nd = N_LIMBS
        d = [plane[l][:, 1:erow - 1] ^ queries[:, l:l + 1]
             for l in range(nd)]                           # nd × [Q, 3s]
        gr = start[:, None] + jnp.arange(wlen, dtype=jnp.int32)[None, :]
        inv_b = gr >= n_valid
        if tomb is not None:
            inv_b = inv_b | tomb
        inv = inv_b.astype(jnp.int32)

        num_keys = 7 if select == "sort" else 3
        out = lax.sort((inv,) + tuple(d) + (gr,),
                       dimension=1, num_keys=num_keys)
        top_inv = out[0][:, :k]
        valid_k = top_inv == 0
        top_limbs = [jnp.where(valid_k, out[1 + l][:, :k],
                               jnp.uint32(0xFFFFFFFF))
                     for l in range(nd)]
        top_idx = jnp.where(valid_k, out[1 + nd][:, :k], -1)
        top_dist = jnp.stack(top_limbs, axis=-1)           # single 3-D build
        tie_a0, tie_a1 = out[1][:, :k + 1], out[2][:, :k + 1]
        tie_av = out[0][:, :k + 1] == 0

    # window certificate (same argument as window_topk, start = 64j);
    # neighbor rows came along in the gathered row — no extra gather.
    if select != "fast2":
        kth_ids = xor_ids(queries, top_dist[:, k - 1])
        cp_k = common_bits(queries, kth_ids)
    else:
        # fast2: exact cp below 64 bits, clamped (lower bound) above —
        # conservative: a clamp can only turn certified → uncertified
        x0 = top_limbs[0][:, k - 1]
        x1 = top_limbs[1][:, k - 1]
        cp_k = jnp.where(x0 != 0, clz32(x0), 32 + clz32(x1))
    certified = _window_certificate(
        queries, cp_k, valid_k[:, k - 1], left_ids, right_ids,
        start > 0, (start + wlen) < n_valid)

    if select in ("fast3", "fast2"):
        # fast3/fast2 exactness: no adjacent (d0, d1) tie among the
        # first k+1 valid sorted rows (a tie anywhere in the sorted
        # order is an adjacent tie; ties past position k cannot change
        # the top-k set or its order).
        tie = jnp.any((tie_a0[:, 1:] == tie_a0[:, :-1])
                      & (tie_a1[:, 1:] == tie_a1[:, :-1])
                      & tie_av[:, 1:] & tie_av[:, :-1], axis=1)
        certified = certified & ~tie
    return top_dist, top_idx, certified


@functools.partial(jax.jit, static_argnames=("k", "select", "cap", "planes",
                                             "fast2_limbs"))
def cascade_topk(sorted_ids, exp_fast, exp_wide, n_valid, queries, lut, *,
                 k: int = 8, select: str = "fast2", cap: int = 512,
                 planes: int = N_LIMBS, fast2_limbs: bool = False):
    """Two-stage certified lookup in ONE device call — the headline
    kernel (bench.py).

    Stage 1: :func:`expanded_topk` over the narrow fast expansion with
    LUT-only positioning.  At the headline geometry (stride 32 →
    96-row windows that sort in 128 padded lanes) ~0.9987 of uniform
    queries certify — ~164 repairs per 131K batch at k=16; narrower
    margins decertify more (stride 24 measured 0.974 — past the
    optimum).  Stage 2: up to ``cap`` uncertified rows are selected ON
    DEVICE (``jnp.nonzero(size=cap)`` — static shape, no host sync, no
    cond) and re-looked-up against the wide stride-64 expansion, whose
    64-row margins certify everything stage 1 missed on non-adversarial
    tables.  Size ``cap`` ≥ a few × the expected stage-1 miss count
    (the 512 default covers the headline geometry ~3×; stage-2 cost is
    insensitive to it).  Rows neither stage certifies (> cap failures,
    or adversarial clustering) come back with ``certified=False`` and
    the caller falls back exactly (lookup_topk's host path).

    This replaces a full-scan fallback that cost 520 ms per batch at
    Q=128×N=1M (the tiled scan serializes ~245 tiny sort steps) with a
    ~0.5 ms always-on second pass.  Returns (dist|None, idx, certified)
    with the :func:`expanded_topk` contract.
    """
    d, idx, cert = expanded_topk(sorted_ids, exp_fast, n_valid, queries,
                                 k=k, select=select, lut=lut, lut_steps=0,
                                 planes=planes, fast2_limbs=fast2_limbs)
    # fill_value=0 pads `bad` with duplicate index 0 when fewer than
    # `cap` rows decertify, so the .at[bad].set scatters below write row
    # 0 repeatedly.  That is deterministic ONLY because every duplicate
    # writes an identical value by construction: for a padded entry
    # was_bad=False, so the write is the row's own current value (and
    # the cert update ORs a True with anything).  If a future edit makes
    # per-row scatter values diverge (e.g. mixes in per-slot data), the
    # duplicates become racy — use a unique fill row or mask first.
    # (Same invariant as _lookup_engine's compaction in core/search.py.)
    bad = jnp.nonzero(~cert, size=cap, fill_value=0)[0]
    qb = jnp.take(queries, bad, axis=0)
    # LUT-started bounded positioning for the rescue rows too: the
    # sequential probe-gather steps are the stage's serial cost (full
    # depth = 17-21 steps; the budget search ≈ 6), and a mispositioned
    # rescue on an adversarial table merely stays uncertified — the
    # residual flag routes it to the caller's exact fallback, so
    # soundness never depends on the LUT.  (Full-depth stage 2 measured
    # 3× the whole delta-cascade cost at cap=4096 in the churn round.)
    d2, i2, c2 = expanded_topk(sorted_ids, exp_wide, n_valid, qb,
                               k=k, select=select, lut=lut, lut_steps=None,
                               planes=planes, fast2_limbs=fast2_limbs)
    was_bad = jnp.take(~cert, bad)
    take = was_bad & c2
    old_idx = jnp.take(idx, bad, axis=0)
    idx = idx.at[bad].set(jnp.where(take[:, None], i2, old_idx))
    if d is not None and d2 is not None:
        if isinstance(d, tuple):               # fast2_limbs 2-D planes
            d = tuple(
                dp.at[bad].set(jnp.where(take[:, None], d2p,
                                         jnp.take(dp, bad, axis=0)))
                for dp, d2p in zip(d, d2))
        else:
            old_d = jnp.take(d, bad, axis=0)
            d = d.at[bad].set(jnp.where(take[:, None, None], d2, old_d))
    cert = cert.at[bad].set(jnp.take(cert, bad) | c2)
    return d, idx, cert


@functools.partial(jax.jit, static_argnames=("k", "window", "select",
                                             "lut_steps", "tile"))
def _lookup_topk_device(sorted_ids, expanded, n_valid, queries, lut, *,
                        k, window, select, lut_steps, tile):
    """Fast lookup + device-side exact fallback in ONE device call.

    ``lax.cond`` on the all-certified predicate keeps the common path
    free of the O(N) scan (same pattern as the sharded shard-local
    fallback, parallel/sharded.py); when any query decertifies, the
    whole batch is rescanned and certified rows keep their window
    result.  No host sync — the data-dependent choice stays on device.
    """
    if expanded is not None:
        dist, idx, cert = expanded_topk(sorted_ids, expanded, n_valid,
                                        queries, k=k, select=select,
                                        lut=lut, lut_steps=lut_steps)
    else:
        dist, idx, cert = window_topk(sorted_ids, n_valid, queries, k=k,
                                      window=window, lut=lut,
                                      lut_steps=(LUT_BUCKET_STEPS
                                                 if lut_steps is None
                                                 else lut_steps))
    valid_rows = jnp.arange(sorted_ids.shape[0]) < n_valid

    def exact(_):
        d2, i2 = xor_topk(queries, sorted_ids, k=k, tile=tile,
                          valid=valid_rows)
        keep = cert[:, None]
        i_out = jnp.where(keep, idx, i2)
        if dist is None:                      # fast2 carries no distances
            return (i_out,)
        return (i_out, jnp.where(keep[..., None], dist, d2))

    def fast(_):
        return (idx,) if dist is None else (idx, dist)

    out = lax.cond(jnp.all(cert), fast, exact, operand=None)
    if dist is None:
        return None, out[0], jnp.ones_like(cert)
    return out[1], out[0], jnp.ones_like(cert)


_DONATING_LOOKUP = None


def _donating_lookup_topk():
    """The same compiled program as :func:`_lookup_topk_device` with the
    per-wave query buffer donated (``donate_argnums=3`` — round-20 wave
    pipeline: the wave builder uploads a fresh [Q,5] buffer per wave
    and never re-reads it, so the backend may reuse its pages instead
    of allocating per launch).  On the CPU backend donation is
    unimplemented (and our query buffer never aliases the [Q,k,·]
    outputs, so XLA would warn "donated buffers were not usable") —
    there the plain jit is returned and the knob is a no-op."""
    global _DONATING_LOOKUP
    if _DONATING_LOOKUP is None:
        if jax.default_backend() == "cpu":
            _DONATING_LOOKUP = _lookup_topk_device
        else:
            import warnings
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            _DONATING_LOOKUP = jax.jit(
                _lookup_topk_device.__wrapped__,
                static_argnames=("k", "window", "select", "lut_steps",
                                 "tile"),
                donate_argnums=(3,))
    return _DONATING_LOOKUP


def lookup_topk(sorted_ids, n_valid, queries, *, k: int = 8, window: int = 128,
                fallback: bool = True, lut=None,
                lut_steps=None, expanded=None,
                select: str = "fast3", host_fallback: bool = False,
                donate_queries: bool = False):
    """Window lookup with exact fallback: uncertified queries re-run
    through the full-scan oracle so the result is always exact (when
    ``fallback=True``; with ``fallback=False`` rows where the returned
    ``certified`` mask is False may be inexact).

    With ``expanded`` (from :func:`expand_table`) the fast row-gather
    path (:func:`expanded_topk`) replaces the per-element window gather.

    The default fallback is resolved ON DEVICE (``lax.cond`` exact
    rescan) so the certified common case costs exactly one device call
    with no host round-trip.  ``host_fallback=True`` keeps the old
    host-driven path — it fetches the certificate and rescans only the
    uncertified rows, which is cheaper when misses are frequent *and*
    the batch is huge, at the price of a blocking device→host sync per
    call.  Returns (dist [Q,k,5], idx [Q,k] int32 into the *sorted*
    table, certified [Q] bool).

    ``donate_queries=True`` (round-20 wave pipeline) donates the query
    buffer to the device-fallback jit — callers must pass a buffer they
    own and never re-read (the wave builder's per-wave upload).  No-op
    on CPU and on the host-fallback paths (which re-read ``queries``).
    """
    # Same OOM guard as the sharded shard-local fallback
    # (parallel/sharded.py): past 8M rows a 4096-row tile's [Q, 4104]x7
    # u32 sort temps cannot sit alongside the resident table, and the
    # exact branch's buffers are allocated even when lax.cond never
    # takes it.  Small tile past 8M — the branch is rare, so its
    # throughput is secondary to it being allocatable.
    n_rows = int(sorted_ids.shape[0])
    tile = max(1, min(4096 if n_rows <= 8_000_000 else 512, n_rows))
    if fallback and not host_fallback:
        fn = _donating_lookup_topk() if donate_queries \
            else _lookup_topk_device
        return fn(sorted_ids, expanded, n_valid, queries,
                  lut, k=k, window=window, select=select,
                  lut_steps=lut_steps, tile=tile)
    if expanded is not None:
        dist, idx, cert = expanded_topk(sorted_ids, expanded, n_valid,
                                        queries, k=k, select=select,
                                        lut=lut, lut_steps=lut_steps)
    else:
        dist, idx, cert = window_topk(sorted_ids, n_valid, queries, k=k,
                                      window=window, lut=lut,
                                      lut_steps=(LUT_BUCKET_STEPS
                                                 if lut_steps is None
                                                 else lut_steps))
    if not fallback:
        return dist, idx, cert
    cert_host = jax.device_get(cert)
    if cert_host.all():
        return dist, idx, cert
    bad = jnp.nonzero(~cert)[0]
    valid_rows = jnp.arange(sorted_ids.shape[0]) < n_valid
    fb_dist, fb_idx = xor_topk(queries[bad], sorted_ids, k=k, tile=tile,
                               valid=valid_rows)
    if dist is not None:                      # fast2 returns no distances
        dist = dist.at[bad].set(fb_dist)
    idx = idx.at[bad].set(fb_idx)
    return dist, idx, jnp.ones_like(cert)


# ---------------------------------------------------------------------------
# Churn path: append+tombstone lookups without re-sorting (SURVEY §7
# "incremental updates": append+tombstone slabs with periodic compaction,
# not per-insert device round-trips; reference mutation path
# src/routing_table.cpp:204-262).
#
# The immutable base (sorted + expanded table) absorbs mutations two ways:
#   evictions  → one bit in a packed tombstone mask over sorted positions,
#                folded into the window kernel's invalid lanes
#                (expanded_topk tomb_bits) — dead rows stay in the array
#                as mere sort keys;
#   inserts    → rows of a fixed-capacity *delta slab*, kept as its own
#                mini sorted+expanded table (re-sorted per mutation
#                batch — one cheap device sort at slab sizes, amortized
#                over the batch; a brute-force delta scan would be
#                O(Q·D) and dominate the whole lookup past D≈1K).
# A lookup is then: tombstone-masked window top-k over the base, window
# top-k over the delta, and one [Q, 2k]-wide merge sort.  Correctness
# never depends on churn volume — heavily-tombstoned windows simply
# decertify into the exact fallback — so compaction (full re-sort +
# re-expand) is purely a performance policy, scheduled by core/table.py.
# ---------------------------------------------------------------------------

_ENC_SENT = 0x7FFFFFFF                  # invalid-lane sentinel (sorts last)


def _fallback_tile(n_rows: int, q: int) -> int:
    """Exact-scan tile for a lax.cond fallback branch: the branch's
    buffers are ALLOCATED even when never taken, and one merge step
    holds ~Q·(tile+k)·7 uint32 sort temps.  Cap the product at ~1 GiB
    (tile floor 512 — the branch is rare, so its throughput is
    secondary to it being allocatable); same rule served the >8M-row
    guard in lookup_topk / parallel/sharded.py, generalized to large
    query batches."""
    t = 4096
    while t > 512 and q * t * 28 > (1 << 30):
        t //= 2
    return max(1, min(n_rows, t))


def _resolve_merge_pack(pack, k: int) -> int:
    """``merge_pack="auto"`` → as many queries per 128-lane physical row
    as k allows (P·k ≤ 128; 16 at the protocol k=8) on TPU, where the
    minor-dim pad tax the packing amortizes exists — and 1 elsewhere:
    on cpu the packed merge STAGE measured ~10× the unpacked stage
    (16.6 ms vs ~1.6 ms over the no-merge variant; −15 ms ≈ −3.5% at
    the whole-round level — captures/churn_packed.json), the same
    backend split window_topk's ``select="auto"`` makes.  Any int ≥ 1
    is valid — P=1 is the unpacked merge.  Pure resolution — the
    telemetry lives at the jit boundary (``churn_lookup_topk`` counts
    ``dht_churn_merge_pack_resolved_total{pack=}`` once per trace, so
    that counter records which pack paths got COMPILED this process;
    the per-call path counter is core/table.ChurnView.lookup's)."""
    if pack == "auto":
        return (max(1, 128 // k)
                if jax.default_backend() == "tpu" else 1)
    p = int(pack)
    if p < 1:
        raise ValueError(f"merge_pack must be >= 1 (got {pack!r})")
    return p


def packed_churn_merge(m_dist, m_idx, d_dist, d_idx, n_base, *, k: int,
                       nl: int, pack: int = 1):
    """Lane-packed base∪delta candidate merge — the churn round's
    padding-tax amortizer.

    The merge operands are intrinsically k lanes wide ([Q, k] carried
    distance planes + index planes), and TPU tiled layout pads every
    minor dim to 128 lanes: at the protocol k=8 each elementwise mask /
    sentinel / sort step moves 16× the useful bytes — measured ~8 ms of
    the 13.6 ms churny-vs-static gap at 131K queries
    (benchmarks/exp_churn2_r5.py, VERDICT r5 weak #1).  The standard
    lane-occupancy trick from batched serving kernels applies because
    the per-query merges are independent: pack P queries' k-lane planes
    into one [Q/P, P·k] physical row (P·k = 128 exactly at k=8), pay
    the pad once per P queries, and keep the merge a single row-wise
    ``lax.sort`` by prepending a query-slot key — within a packed row
    the sort groups each query's 2k candidates contiguously and orders
    them by exactly the comparison the unpacked merge used, so the
    extracted prefixes are bit-identical for every P (pinned across
    pack widths, ragged Q, and tombstone densities in
    tests/test_table_churn.py).  Ragged Q pads the tail with sentinel
    slots (enc = _ENC_SENT, all-ones distances) that sort behind every
    real candidate of their slot and are sliced off on unpack.

    Args: ``m_dist``/``d_dist`` carried distance keys — a tuple of nl
    2-D [Q, k] planes (the fast2_limbs form) or an [Q, k, nl] stack;
    ``m_idx``/``d_idx`` int32 [Q, k] candidate encodings (-1 invalid,
    base sorted positions / delta sorted positions); ``n_base`` the
    base table row count (delta encodings come back offset by it, the
    churn_lookup_topk contract).

    Returns ``(enc [Q, w], limbs [nl × [Q, w]])`` — the first
    w = min(k+1, 2k) rows of each query's merged order (k results + one
    lookahead row for the fast2 tie check), masked lanes carrying
    _ENC_SENT / all-ones.
    """
    Q = m_idx.shape[0]
    big = jnp.uint32(0xFFFFFFFF)
    w = min(k + 1, 2 * k)
    P = int(pack)
    QB = -(-Q // P)
    Qp = QB * P

    def _pl(x, l):
        return x[l] if isinstance(x, (tuple, list)) else x[..., l]

    def pk(x, fill):
        if Qp != Q:
            x = jnp.concatenate(
                [x, jnp.full((Qp - Q, k), fill, x.dtype)], axis=0)
        return x.reshape(QB, P * k)

    # masking runs on the packed rows: these wheres (and the sort
    # below) are the ops the [Q, k] layout paid the 128-lane pad on
    mi = pk(m_idx, jnp.int32(-1))
    di = pk(d_idx, jnp.int32(-1))
    mv = mi >= 0
    dv = di >= 0
    enc = jnp.concatenate([jnp.where(mv, mi, _ENC_SENT),
                           jnp.where(dv, di + n_base, _ENC_SENT)], axis=1)
    limbs = tuple(
        jnp.concatenate([jnp.where(mv, pk(_pl(m_dist, l), big), big),
                         jnp.where(dv, pk(_pl(d_dist, l), big), big)],
                        axis=1)
        for l in range(nl))
    if P > 1:
        # slot-segmented sort: the slot key confines every comparison
        # to one query's segment, so adding it changes nothing about
        # the within-query order.  Lanes with fully-equal key tuples
        # are byte-identical in every operand (the all-ones sentinel),
        # so the unstable sort cannot change extracted values.
        slot = jnp.repeat(jnp.arange(P, dtype=jnp.int32), k)
        slot = jnp.broadcast_to(jnp.concatenate([slot, slot])[None, :],
                                (QB, 2 * P * k))
        out = lax.sort((slot,) + limbs + (enc,), dimension=1,
                       num_keys=nl + 2)[1:]
    else:
        out = lax.sort(limbs + (enc,), dimension=1, num_keys=nl + 1)

    def unpk(a):
        # slot s owns lanes [2k·s, 2k·(s+1)) after the segmented sort
        return a.reshape(QB, P, 2 * k)[:, :, :w].reshape(Qp, w)[:Q]

    return unpk(out[nl]), [unpk(out[l]) for l in range(nl)]


@functools.partial(jax.jit, static_argnames=("k", "select", "lut_steps",
                                             "d_lut_steps", "planes",
                                             "d_cap", "merge_pack"))
def churn_lookup_topk(sorted_ids, expanded, n_valid, tomb_bits,
                      d_sorted, d_expanded, d_n_valid, queries,
                      lut=None, d_lut=None, d_exp_wide=None, *, k: int = 8,
                      select: str = "fast3", lut_steps=None,
                      d_lut_steps=None, planes: int = N_LIMBS,
                      d_cap: int = 1024, merge_pack="auto"):
    """Exact k XOR-closest over (live base rows ∪ delta slab).

    Args: base table as in :func:`expanded_topk` (``expanded`` must use
    a stride divisible by 32), ``tomb_bits`` packed uint32 [ceil(N/32)]
    over base sorted positions (1 = dead); ``d_sorted``/``d_expanded``/
    ``d_n_valid`` the delta slab as its own small sorted+expanded table
    (any stride); optional positioning LUTs (+ ``*_steps``, forwarded
    to :func:`expanded_topk` — pass 0 for LUT-only positioning when
    the LUT bits match the table size, the big win at bench scale).

    Returns (dist, idx [Q,k] int32, certified [Q] all-True).  ``idx``
    encodes the source: values in [0, N) are *sorted positions* of the
    base; values in [N, N+D) are ``N + delta sorted position``; -1 =
    fewer than k live rows exist.  ``dist`` is [Q,k,5] for
    ``select="fast3"``/``"sort"`` (full limbs ride the window sorts —
    no extra gathers) and ``None`` for ``"fast2"`` (the
    findClosestNodes contract: nodes, not distances).

    ``merge_pack`` sets the lane-packing width of the final merge
    (:func:`packed_churn_merge`): ``"auto"`` packs 128//k queries per
    physical row on TPU (the 128-lane padding-tax amortizer — P=16 at
    k=8) and resolves to 1 elsewhere (no pad tax to amortize; measured
    slightly negative on cpu).  Any int ≥ 1 forces that width.
    Results are bit-identical for every width.

    Everything is gather-free past the window row fetches: the merge
    sorts the *carried* distance keys — 6 operands for fast3, 3 for
    fast2 (top-64 bits + source key).  fast2's 64-bit merge can tie
    (p≈2⁻⁴⁷·k per query); ties are detected on the merged k+1 prefix
    and repaired under a ``lax.cond`` that re-merges on full gathered
    distances — allocated but ~never executed, like the exact-scan
    fallbacks that repair uncertified window rows (tombstone-aware for
    the base; ``_fallback_tile`` bounds every branch's buffers).  The
    result is unconditionally exact — bit-identical to a full re-sort
    of the mutated id set (tests/test_table_churn.py proves it against
    that oracle).
    """
    N = sorted_ids.shape[0]
    D = d_sorted.shape[0]
    Q = queries.shape[0]
    n_valid = jnp.asarray(n_valid, jnp.int32)
    d_n_valid = jnp.asarray(d_n_valid, jnp.int32)
    big = jnp.uint32(0xFFFFFFFF)
    fast2 = select == "fast2"
    nl = 2 if fast2 else N_LIMBS

    m_dist, idx, cert = expanded_topk(sorted_ids, expanded, n_valid,
                                      queries, k=k, select=select, lut=lut,
                                      lut_steps=lut_steps,
                                      tomb_bits=tomb_bits, fast2_limbs=True,
                                      planes=planes)

    def exact(_):
        live = (jnp.arange(N) < n_valid) & ~unpack_tomb_bits(tomb_bits, N)
        dx, i2 = xor_topk(queries, sorted_ids, k=k,
                          tile=_fallback_tile(N, Q), valid=live)
        keep = cert[:, None]
        i_out = jnp.where(keep, idx, i2)
        if fast2:
            return (i_out, tuple(jnp.where(keep, m_dist[l], dx[..., l])
                                 for l in range(nl)))
        return (i_out, jnp.where(keep[..., None], m_dist, dx[..., :nl]))

    m_idx, m_dist = lax.cond(jnp.all(cert), lambda _: (idx, m_dist),
                             exact, operand=None)

    if d_exp_wide is not None:
        # NARROW-delta cascade: the delta slab takes a stride-16
        # expansion (48-row windows sort in 64 padded lanes — measured
        # 27× cheaper per 131K batch than stride 32's 128-lane sorts)
        # whose ~0.7% uncertified rows are repaired on device against
        # the wide expansion, exactly like the headline cascade_topk.
        # Without this, one decertified row would flip the whole batch
        # into the O(Q·D) exact scan every round.
        dd, d_idx, d_cert = cascade_topk(
            d_sorted, d_expanded, d_exp_wide, d_n_valid, queries, d_lut,
            k=k, select=select, cap=d_cap, planes=planes, fast2_limbs=True)
    else:
        dd, d_idx, d_cert = expanded_topk(d_sorted, d_expanded, d_n_valid,
                                          queries, k=k, select=select,
                                          lut=d_lut, lut_steps=d_lut_steps,
                                          fast2_limbs=True, planes=planes)

    def d_exact(_):
        dx, i2 = xor_topk(queries, d_sorted, k=k,
                          tile=_fallback_tile(D, Q),
                          valid=jnp.arange(D) < d_n_valid)
        keep = d_cert[:, None]
        i_out = jnp.where(keep, d_idx, i2)
        if fast2:
            return (i_out, tuple(jnp.where(keep, dd[l], dx[..., l])
                                 for l in range(nl)))
        return (i_out, jnp.where(keep[..., None], dd, dx[..., :nl]))

    d_idx, dd = lax.cond(jnp.all(d_cert), lambda _: (d_idx, dd),
                         d_exact, operand=None)

    # merge: one slot-segmented sort over P packed queries' 2k
    # candidates per physical row on the CARRIED distance keys + a
    # source key (packed_churn_merge — the 128-lane padding-tax
    # amortizer).  Invalid lanes get all-ones limbs + the ENC sentinel;
    # a *real* candidate with an all-ones distance still wins via the
    # smaller enc key.  Live ids are unique across base and delta
    # (core/table.py re-adds a revived id to the delta only while its
    # base position is tombstoned), so full distances never tie and
    # fast3's 5-limb merge order is exact.
    m_valid = m_idx >= 0
    d_valid = d_idx >= 0
    P = _resolve_merge_pack(merge_pack, k)
    # trace-time (runs once per compilation of this shape): record which
    # pack path got compiled
    telemetry.get_registry().counter(
        "dht_churn_merge_pack_resolved_total", pack=P).inc()
    enc_p, limbs_p = packed_churn_merge(m_dist, m_idx, dd, d_idx, N,
                                        k=k, nl=nl, pack=P)
    enc_k = enc_p[:, :k]
    ok = enc_k != _ENC_SENT

    if not fast2:
        f_idx = jnp.where(ok, enc_k, -1)
        f_dist = jnp.stack([jnp.where(ok, limbs_p[l][:, :k], big)
                            for l in range(nl)], axis=-1)
        return f_dist, f_idx, jnp.ones((Q,), bool)

    # fast2: the merge ordered on 64 distance bits only — an adjacent
    # tie among the first k+1 merged rows means the true 160-bit order
    # is undetermined.  Repair by re-merging the same 2k candidates on
    # FULL distances (id gathers live only inside this ~never-taken
    # branch, unpacked — its cost does not matter, its allocation does:
    # _fallback_tile bounds the rest of the branch family).
    t0, t1, tv = limbs_p[0], limbs_p[1], enc_p != _ENC_SENT
    tie = jnp.any((t0[:, 1:] == t0[:, :-1]) & (t1[:, 1:] == t1[:, :-1])
                  & tv[:, 1:] & tv[:, :-1])

    def exact_merge(_):
        enc_all = jnp.concatenate(
            [jnp.where(m_valid, m_idx, _ENC_SENT),
             jnp.where(d_valid, d_idx + N, _ENC_SENT)], axis=1)
        m_ids = jnp.take(sorted_ids, jnp.clip(m_idx, 0, N - 1).reshape(-1),
                         axis=0).reshape(Q, k, N_LIMBS)
        d_ids = jnp.take(d_sorted, jnp.clip(d_idx, 0, D - 1).reshape(-1),
                         axis=0).reshape(Q, k, N_LIMBS)
        fm = xor_ids(queries[:, None, :], m_ids)
        fd = xor_ids(queries[:, None, :], d_ids)
        ops_f = tuple(
            jnp.concatenate([jnp.where(m_valid, fm[..., l], big),
                             jnp.where(d_valid, fd[..., l], big)], axis=1)
            for l in range(N_LIMBS)
        ) + (enc_all,)
        o2 = lax.sort(ops_f, dimension=1, num_keys=N_LIMBS + 1)
        return o2[N_LIMBS][:, :k]

    enc_k = lax.cond(tie, exact_merge, lambda _: enc_k, operand=None)
    ok = enc_k != _ENC_SENT
    f_idx = jnp.where(ok, enc_k, -1)
    return None, f_idx, jnp.ones((Q,), bool)
