"""Sorted-table XOR nearest-neighbor lookup — the fast path.

The reference finds closest nodes two ways: walking k-buckets outward
(src/routing_table.cpp:109-150) or walking a lexicographically-sorted
map outward from ``lower_bound(id)`` picking the XOR-closer side each
step (``NodeCache::getCachedNodes``, src/node_cache.cpp:41-74).  Both
exploit the same property this module vectorizes:

  In lexicographic order, the common-prefix length cp(q, ·) is unimodal
  around q's insertion position, and every node with cp ≥ L forms one
  contiguous run containing that position.  All nodes inside that run
  are XOR-closer to q than any node outside it.

So the k XOR-closest nodes live in a small *window* of the sorted table
around q's position, and we can prove it per query:

  certificate:  cb(q, kth result) > cb(q, nearest excluded neighbor)
                on each side that has excluded nodes.

When the certificate holds (virtually always for random SHA1 ids and
window ≥ 8k), the window result equals the exact full scan; failures
fall back to ops/xor_topk.  This turns the O(Q·N) scan into
O(Q·(log N + W)) — the difference between 1M×10M = 10^13 limb ops and
~1M×300 = 3·10^8, which is what makes the BASELINE.json north star
(<1 ms amortized per lookup) reachable.

All steps are static-shape, batched, and jit/shard_map friendly:
binary search is a fixed ``ceil(log2 N)``-step ``fori_loop``; the window
merge is one 7-key lexicographic sort (see ops/xor_topk.py for the key
layout) or the pallas selection kernel (ops/pallas_select.py).

Negative result (recorded so it isn't retried): fusing the window
*gather* into a pallas kernel — DMAing each query's window straight
from the HBM-resident table via scalar-prefetched start offsets — does
not work on TPU.  Mosaic requires slice offsets aligned to the memref
tiling (1024 elements for 1-D int32, 8 sublanes for 2-D), so arbitrary
per-query window starts either fail to compile or force the window to
be widened ~8× to the alignment grid, destroying the HBM-traffic
saving that motivated the fusion.  XLA's general gather handles the
unaligned access pattern natively; the win that *was* available —
replacing the post-gather sort with VPU min-extraction — is
ops/pallas_select.py.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from .ids import N_LIMBS, xor_ids, common_bits, lex_lt
from .xor_topk import xor_topk

_U32 = jnp.uint32


@functools.partial(jax.jit, static_argnames=())
def sort_table(ids, valid=None):
    """Sort id rows lexicographically; invalid rows sink to the end.

    Returns (sorted_ids [N,5], perm [N] int32 original row of each sorted
    row, n_valid int32).  ``perm`` is -1 on rows that were invalid.
    """
    N = ids.shape[0]
    if valid is None:
        valid = jnp.ones((N,), dtype=bool)
    inv = (~valid).astype(jnp.int32)
    idx = jnp.arange(N, dtype=jnp.int32)
    ops_in = (inv, ids[:, 0], ids[:, 1], ids[:, 2], ids[:, 3], ids[:, 4], idx)
    out = lax.sort(ops_in, dimension=0, num_keys=6)
    sorted_ids = jnp.stack(out[1:6], axis=-1)
    perm = jnp.where(out[0] == 0, out[6], -1)
    n_valid = jnp.sum(valid.astype(jnp.int32))
    return sorted_ids, perm, n_valid


LUT_BITS = 16
# binary-search depth inside one LUT bucket: buckets of a 2^16-way
# partition of N uniform ids are ~N/2^16 rows; 4096 (2^12) is a huge
# overshoot for any realistic N, and an adversarial bucket larger than
# that merely yields a wrong window that the exactness certificate
# catches (→ full-scan fallback).  Measured on v5e-lite @ N=1M the LUT
# path is within noise of the plain 21-step search (the per-step gather
# fuses well), so it stays opt-in — it pays when N grows enough that
# log2(N) - LUT_BUCKET_STEPS widens.
LUT_BUCKET_STEPS = 13


@jax.jit
def build_prefix_lut(sorted_ids, n_valid):
    """Top-16-bit prefix → first sorted row with that prefix or greater.

    Shrinks the per-query binary search from ceil(log2 N)+1 sequential
    gather steps to LUT_BUCKET_STEPS, which is where a third of the
    lookup wall-clock goes at N=1M.  Invalid rows (sorted to the end)
    get the sentinel prefix 2^16 so every real prefix resolves below
    n_valid.  Returns int32 [2^16 + 1]; entry [p+1] bounds bucket p.
    """
    N = sorted_ids.shape[0]
    keys = (sorted_ids[:, 0] >> jnp.uint32(32 - LUT_BITS)).astype(jnp.int32)
    keys = jnp.where(jnp.arange(N) < jnp.asarray(n_valid, jnp.int32),
                     keys, jnp.int32(1 << LUT_BITS))
    probes = jnp.arange((1 << LUT_BITS) + 1, dtype=jnp.int32)
    return jnp.searchsorted(keys, probes, side="left").astype(jnp.int32)


def _lower_bound(sorted_ids, queries, n_valid, lut=None,
                 lut_steps: int = LUT_BUCKET_STEPS):
    """First index i in [0, n_valid] with sorted_ids[i] >= q, batched.

    Fixed-depth binary search (static ceil(log2 N)+1 steps) — no
    data-dependent control flow, so it stays one fused XLA loop.  With a
    prefix ``lut`` (build_prefix_lut) the search starts inside the
    query's 2^16-way bucket and needs only LUT_BUCKET_STEPS steps.
    """
    N = sorted_ids.shape[0]
    Q = queries.shape[0]
    if lut is not None:
        p = (queries[:, 0] >> jnp.uint32(32 - LUT_BITS)).astype(jnp.int32)
        lo = jnp.take(lut, p)
        hi = jnp.take(lut, p + 1)
        steps = lut_steps
    else:
        steps = max(1, math.ceil(math.log2(max(N, 2))) + 1)
        lo = jnp.zeros((Q,), jnp.int32)
        hi = jnp.broadcast_to(jnp.asarray(n_valid, jnp.int32), (Q,))

    def body(_, lohi):
        lo, hi = lohi
        mid = (lo + hi) // 2
        mid_ids = jnp.take(sorted_ids, jnp.clip(mid, 0, N - 1), axis=0)
        lt = lex_lt(mid_ids, queries)   # mid < q, 5-limb lexicographic
        go_right = lt & (lo < hi)
        new_lo = jnp.where(go_right, mid + 1, lo)
        new_hi = jnp.where(go_right | (lo >= hi), hi, mid)
        return new_lo, new_hi

    lo, hi = lax.fori_loop(0, steps, body, (lo, hi))
    return lo


@functools.partial(jax.jit,
                   static_argnames=("k", "window", "select", "lut_steps"))
def window_topk(sorted_ids, n_valid, queries, *, k: int = 8, window: int = 128,
                select: str = "auto", lut=None,
                lut_steps: int = LUT_BUCKET_STEPS):
    """k XOR-closest among the first n_valid rows of a sorted table,
    searched only within a `window`-wide slice around each query's
    sorted position, plus a per-query exactness certificate.

    ``select`` picks the in-window top-k engine: ``"sort"`` = 7-key
    ``lax.sort``; ``"pallas"`` = the VPU min-extraction kernel
    (ops/pallas_select.py); ``"auto"`` = pallas on TPU, sort elsewhere.
    Both are exact and bit-identical (tests/test_topk.py).  ``lut`` is
    an optional prefix table from :func:`build_prefix_lut` that
    shortens the positioning search; a misplaced window from an
    overflowing LUT bucket is caught by the certificate.

    Returns:
      dist      [Q, k, 5] uint32 (all-ones beyond n_valid results)
      idx       [Q, k] int32 indices into the *sorted* table (-1 = none)
      certified [Q] bool — True ⇒ provably equal to the exact full scan
    """
    if window < k:
        raise ValueError(f"window ({window}) must be >= k ({k})")
    if select == "auto":
        select = "pallas" if jax.default_backend() == "tpu" else "sort"
    N = sorted_ids.shape[0]
    Q = queries.shape[0]
    n_valid = jnp.asarray(n_valid, jnp.int32)

    pos = _lower_bound(sorted_ids, queries, n_valid, lut=lut,
                       lut_steps=lut_steps)

    # slide the window to stay inside [0, n_valid) as much as possible
    start = jnp.clip(pos - window // 2, 0, jnp.maximum(n_valid - window, 0))
    offs = jnp.arange(window, dtype=jnp.int32)
    raw = start[:, None] + offs[None, :]                     # [Q, W]
    inv = (raw >= n_valid).astype(jnp.int32)
    gidx = jnp.clip(raw, 0, N - 1)
    win_ids = jnp.take(sorted_ids, gidx.reshape(-1), axis=0).reshape(Q, window, N_LIMBS)

    dist = xor_ids(queries[:, None, :], win_ids)
    if select == "pallas":
        from .pallas_select import lex_topk_select
        sel = lex_topk_select(dist, inv, k=k,
                              interpret=jax.default_backend() != "tpu")
        found = sel >= 0
        selc = jnp.clip(sel, 0, window - 1)
        top_inv = (~found).astype(jnp.int32)
        top_idx = jnp.where(found, jnp.take_along_axis(raw, selc, axis=1), -1)
        top_dist = jnp.where(
            found[..., None],
            jnp.take_along_axis(dist, selc[..., None], axis=1),
            jnp.uint32(0xFFFFFFFF))
    else:
        ops_in = (
            inv,
            dist[..., 0], dist[..., 1], dist[..., 2], dist[..., 3],
            dist[..., 4],
            raw,
        )
        out = lax.sort(ops_in, dimension=1, num_keys=7)
        top_inv = out[0][:, :k]
        top_dist = jnp.stack(out[1:6], axis=-1)[:, :k]
        top_idx = jnp.where(top_inv == 0, out[6][:, :k], -1)
        top_dist = jnp.where((top_inv == 0)[..., None], top_dist,
                             jnp.full_like(top_dist, 0xFFFFFFFF))

    # ---- exactness certificate ------------------------------------------
    # Nodes excluded on the left are all at sorted index < start; the
    # closest-in-order one is start-1 and (prefix monotonicity) carries the
    # maximal common prefix cbL among them.  Any excluded node's distance
    # is >= 2^(159-cbL), while the kth window result's distance is
    # < 2^(160-cp_k); cp_k > cbL makes every window top-k strictly closer
    # than every excluded node.  Symmetrically on the right.
    # recover the kth id from its distance (id = q ^ dist)
    kth_dist = top_dist[:, k - 1]
    kth_valid = top_inv[:, k - 1] == 0
    kth_ids = xor_ids(queries, kth_dist)
    cp_k = common_bits(queries, kth_ids)

    left_exists = start > 0
    right_exists = (start + window) < n_valid
    left_ids = jnp.take(sorted_ids, jnp.clip(start - 1, 0, N - 1), axis=0)
    right_ids = jnp.take(sorted_ids, jnp.clip(start + window, 0, N - 1), axis=0)
    cbL = common_bits(queries, left_ids)
    cbR = common_bits(queries, right_ids)

    covers_all = (~left_exists) & (~right_exists)
    ok_left = (~left_exists) | (cp_k > cbL)
    ok_right = (~right_exists) | (cp_k > cbR)
    certified = covers_all | (kth_valid & ok_left & ok_right)
    return top_dist, top_idx, certified


def lookup_topk(sorted_ids, n_valid, queries, *, k: int = 8, window: int = 128,
                fallback: bool = True, lut=None,
                lut_steps: int = LUT_BUCKET_STEPS):
    """Window lookup with exact fallback: uncertified queries re-run
    through the full-scan oracle so the result is always exact (when
    ``fallback=True``; with ``fallback=False`` rows where the returned
    ``certified`` mask is False may be inexact).

    Host-level driver (the fallback set is data-dependent); the common
    path is a single device call.  Returns (dist [Q,k,5],
    idx [Q,k] int32 into the *sorted* table, certified [Q] bool).
    """
    dist, idx, cert = window_topk(sorted_ids, n_valid, queries, k=k,
                                  window=window, lut=lut,
                                  lut_steps=lut_steps)
    if not fallback:
        return dist, idx, cert
    cert_host = jax.device_get(cert)
    if cert_host.all():
        return dist, idx, cert
    bad = jnp.nonzero(~cert)[0]
    valid_rows = jnp.arange(sorted_ids.shape[0]) < n_valid
    fb_dist, fb_idx = xor_topk(queries[bad], sorted_ids, k=k, valid=valid_rows)
    dist = dist.at[bad].set(fb_dist)
    idx = idx.at[bad].set(fb_idx)
    return dist, idx, jnp.ones_like(cert)
