"""Device-resident swarm stepper: tens of thousands of simulated DHT
nodes advanced through a :class:`~opendht_tpu.chaos.FaultPlan` entirely
on device (ISSUE-13 tentpole, ROADMAP item 5).

Per-simulated-node state is batched into flat arrays — node ids
(uint32 [S,5] limbs), liveness + last-seen, **routing-table occupancy
limbs** (160 buckets x 4-bit counts nibble-packed into uint32 [S,20] —
a 100k-node swarm's routing state is 8 MB resident), a parallel
attacker-occupancy plane for eclipse/sybil phases, and the stored-key
replica assignment (int32 [K,R] rows).  One jitted
:func:`swarm_step` launch advances the whole swarm one tick:

- **join/leave storms** — per-node uniform draws against the phase's
  :class:`~opendht_tpu.chaos.Storm` rates;
- **asymmetric partitions** — a [G,G] reachability matrix derived from
  the phase's :class:`~opendht_tpu.chaos.Partition` gates every
  maintenance/refresh/republish interaction (healing = the phase
  ends and the matrix goes all-True);
- **routing maintenance** — the PR-5 fused
  :func:`~opendht_tpu.ops.radix.maintenance_sweep` is the tick kernel:
  vmapped over a rotating sample of nodes it computes each sampled
  node's TRUE per-bucket reachable-alive occupancy + staleness against
  the whole population, refilling its table exactly; every other node
  that wins its maintenance draw refreshes to the analytic steady-state
  k-bucket fill ``min(k, reachable >> (b+1))`` (the sweep's exact
  counts pin the analytic model each tick — ``model_err`` in the
  returned metrics is the integer sum of their disagreement over the
  sampled rows);
- **eclipse/sybil poisoning** — attacker entries are admitted into at
  most the FREE slots of each victim bucket (the reference routing
  table's full-bucket admission rule, src/routing_table.cpp:204-262)
  and evicted by the first successful maintenance pass after the
  poison phase ends (3x request expiry);
- **republish** — on calendar ticks, due keys re-resolve their
  closest-R replica set over the currently alive+reachable population
  (one batched XOR top-R, the same 5-limb lexicographic selection the
  shipping ``find_closest_nodes_batched`` kernel performs).

Determinism and the host oracle: the step consumes PRE-DRAWN random
bits (uint32 arrays the driver derives from one seeded PRNG key), so
the jitted step and the scalar-flavored numpy oracle
:func:`swarm_step_host` consume identical entropy and are pinned
**bit-identical** at small N (tests/test_swarm.py); a fixed seed
replays a storm exactly.  All in-step reductions are integer/boolean
(no float accumulation order), so equality is exact, not approximate.

Probes (:func:`lookup_success_probe`, :func:`replica_coverage`) are the
measurement half: a lookup for key ``h`` from source ``s`` succeeds
when ``s`` is alive, its routing bucket toward ``h`` holds at least one
live reachable honest entry (poisoned slots do not count), and at least
one of ``h``'s true closest-R alive nodes is reachable from ``s`` — the
structural form of the PR-9 lookup-success invariant.  The
:class:`SwarmSim` driver publishes both as ``dht_swarm_*`` gauges and
``swarm_verdict``/``chaos_phase`` flight events, so swarm verdicts flow
through the same health/timeline spine as live clusters.
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from .. import chaos, telemetry, tracing
from ..health import DEGRADED, HEALTHY, UNHEALTHY
from .ids import ID_BITS, N_LIMBS, ids_to_bytes

K_BUCKET = 8                     # slots per bucket (TARGET_NODES)
NIB_PER_LIMB = 8                 # 8 x 4-bit counts per uint32 limb
OCC_LIMBS = ID_BITS // NIB_PER_LIMB      # 20 occupancy limbs per node
REPLICAS = 8                     # stored-key replica factor

_U32_MAX = 0xFFFFFFFF

STATE_KEYS = ("ids", "group", "alive", "last_seen", "table_fresh",
              "occ", "poison", "keys", "key_src", "replicas")


# ------------------------------------------------------------- shared math
def _unif(xp, r):
    """uint32 -> float32 in [0, 1): top 24 bits scaled by 2^-24 — every
    value is exactly representable, so device and host agree bit-for-bit."""
    return (r >> xp.uint32(8)).astype(xp.float32) * xp.float32(2.0 ** -24)


def _unpack_occ(xp, limbs):
    """nibble-packed uint32 [..., 20] -> int32 [..., 160] counts."""
    shifts = (xp.arange(NIB_PER_LIMB).astype(xp.uint32) * xp.uint32(4))
    nib = (limbs[..., :, None] >> shifts) & xp.uint32(0xF)
    return nib.reshape(limbs.shape[:-1] + (ID_BITS,)).astype(xp.int32)


def _pack_occ(xp, counts):
    """int32 [..., 160] counts (0..15) -> uint32 [..., 20] limbs."""
    nib = counts.reshape(counts.shape[:-1] + (OCC_LIMBS, NIB_PER_LIMB))
    shifts = (xp.arange(NIB_PER_LIMB).astype(xp.uint32) * xp.uint32(4))
    return xp.sum(nib.astype(xp.uint32) << shifts, axis=-1,
                  dtype=xp.uint32)


def _avail(xp, rc, n_buckets=ID_BITS):
    """Analytic steady-state k-bucket fill: bucket b of a node with
    ``rc`` reachable alive peers holds ~``rc >> (b+1)`` of them (the
    Kademlia prefix-partition), capped at K_BUCKET.  int32 [..., 160]."""
    sh = xp.minimum(xp.arange(n_buckets, dtype=xp.int32) + 1, 31)
    return xp.minimum(rc[..., None] >> sh, K_BUCKET).astype(xp.int32)


def _closest_r(xp, keys, ids, valid, r):
    """Rows of the ``r`` XOR-closest valid ids per key — the batched
    closest-node selection (one 5-limb lexicographic sort per key,
    index tiebreak so the result is unique and device==host).  Invalid
    rows sort last; returns (sel int32 [K,r], sel_valid bool [K,r])."""
    S = ids.shape[0]
    valid = xp.broadcast_to(valid, (keys.shape[0], S))
    d = xp.bitwise_xor(keys[:, None, :], ids[None, :, :])
    dm = xp.where(valid[:, :, None], d, xp.uint32(_U32_MAX))
    idx = xp.broadcast_to(xp.arange(S, dtype=xp.int32), dm.shape[:2])
    order = xp.lexsort((idx, dm[..., 4], dm[..., 3], dm[..., 2],
                        dm[..., 1], dm[..., 0]), axis=-1)
    sel = order[:, :r].astype(xp.int32)
    sel_valid = xp.take_along_axis(valid, sel, axis=1)
    return sel, sel_valid


# ================================================================ device
def _swarm_step_impl(state, now, leave_rate, join_rate, loss,
                     repub_rate, stale_age, reach, poison_on,
                     poison_mask, poison_pressure, repub_on, sweep_idx,
                     rand_node, rand_key):
    import jax
    import jax.numpy as jnp
    from . import radix

    xp = jnp
    ids = state["ids"]
    group = state["group"]
    alive = state["alive"]
    S = ids.shape[0]
    G = reach.shape[0]

    # -- churn: leave/join storms
    u0 = _unif(xp, rand_node[:, 0])
    u1 = _unif(xp, rand_node[:, 1])
    leave = alive & (u0 < leave_rate)
    join = (~alive) & (u1 < join_rate)
    alive2 = (alive & ~leave) | join
    last_seen2 = xp.where(alive2, now, state["last_seen"])

    # -- partition-aware reachable population per node (integer-exact)
    gcount = jnp.zeros((G,), jnp.int32).at[group].add(
        alive2.astype(jnp.int32))
    reach_i = reach.astype(jnp.int32)
    rc_group = xp.sum(reach_i * gcount[None, :], axis=1)
    self_reach = xp.take(xp.diagonal(reach), group)
    rc = rc_group[group] - (alive2 & self_reach).astype(jnp.int32)
    n_alive = xp.sum(alive2.astype(jnp.int32))

    # -- maintenance draw: (1-loss) x reachable fraction
    denom = xp.maximum(n_alive - 1, 1).astype(jnp.float32)
    p_maint = (jnp.float32(1.0) - loss) * (rc.astype(jnp.float32) / denom)
    ok_maint = alive2 & (_unif(xp, rand_node[:, 2]) < p_maint)

    # -- the PR-5 fused maintenance sweep as the tick kernel: exact
    # per-bucket reachable-alive occupancy + staleness for the rotating
    # sample (valid = alive & reachable-from-me & not-me)
    self_ids = xp.take(ids, sweep_idx, axis=0)
    reach_rows = reach[xp.take(group, sweep_idx)]          # [M, G]
    reach_ms = xp.take(reach_rows, group, axis=1)          # [M, S]
    valid_m = (alive2[None, :] & reach_ms
               & (xp.arange(S, dtype=jnp.int32)[None, :]
                  != sweep_idx[:, None]))
    sweep = jax.vmap(radix.maintenance_sweep,
                     in_axes=(0, None, 0, None, None, None, None))
    counts, _last, stale, _targets = sweep(
        self_ids, ids, valid_m, last_seen2, now, stale_age,
        jax.random.PRNGKey(0))
    counts = counts.astype(jnp.int32)

    # -- occupancy planes (nibble-unpacked)
    occ_n = _unpack_occ(xp, state["occ"])
    poi_n = _unpack_occ(xp, state["poison"])
    victim = poison_on & poison_mask
    # sybil admission: only the FREE slots of a bucket admit attacker
    # entries (full-bucket rejection, src/routing_table.cpp:204-262)
    poi2 = xp.where(victim[:, None],
                    xp.minimum(poi_n + poison_pressure,
                               xp.maximum(K_BUCKET - occ_n, 0)),
                    poi_n)
    # attacker entries expire on the first successful maintenance pass
    # once the poison phase is over (sybils stop answering)
    poi3 = xp.where((ok_maint & ~victim)[:, None], 0, poi2)
    av = _avail(xp, rc)
    target = xp.minimum(av, K_BUCKET - poi3)
    occ2 = xp.where(ok_maint[:, None], target, occ_n)
    # exact refill for the swept rows (counts from the fused sweep)
    sweep_fill = xp.minimum(counts,
                            K_BUCKET - xp.take(poi3, sweep_idx, axis=0))
    sweep_fill = xp.where(xp.take(alive2, sweep_idx)[:, None],
                          sweep_fill, 0)
    occ2 = occ2.at[sweep_idx].set(sweep_fill)
    # joiners bootstrap sparse (one known peer per non-empty bucket);
    # dead nodes hold no table
    occ2 = xp.where(join[:, None], xp.minimum(av, 1), occ2)
    occ2 = xp.where(alive2[:, None], occ2, 0)
    poi3 = xp.where(alive2[:, None] & ~join[:, None], poi3, 0)

    fresh = xp.where(ok_maint | join, now, state["table_fresh"])
    fresh = fresh.at[sweep_idx].set(
        xp.where(xp.take(alive2, sweep_idx), now,
                 xp.take(fresh, sweep_idx)))

    # -- republish: due keys re-resolve closest-R over alive+reachable
    replicas = state["replicas"]
    keys = state["keys"]
    key_src = state["key_src"]
    R = replicas.shape[1]

    def do_repub(_):
        due = _unif(xp, rand_key) < repub_rate
        valid_ks = (alive2[None, :]
                    & reach[group[key_src][:, None], group[None, :]])
        sel, sel_valid = _closest_r(xp, keys, ids, valid_ks, R)
        newrep = xp.where(sel_valid, sel, -1)
        return xp.where(due[:, None], newrep, replicas)

    replicas2 = jax.lax.cond(repub_on, do_repub,
                             lambda _: replicas, 0)

    new_state = {
        "ids": ids, "group": group, "alive": alive2,
        "last_seen": last_seen2, "table_fresh": fresh,
        "occ": _pack_occ(xp, occ2), "poison": _pack_occ(xp, poi3),
        "keys": keys, "key_src": key_src, "replicas": replicas2,
    }
    # integer-only metrics (no float accumulation order): ratios are
    # derived host-side
    analytic_at_sweep = xp.take(target, sweep_idx, axis=0)
    swept_alive = xp.take(alive2, sweep_idx)
    metrics = {
        "n_alive": n_alive,
        "n_leave": xp.sum(leave.astype(jnp.int32)),
        "n_join": xp.sum(join.astype(jnp.int32)),
        "n_maint_ok": xp.sum(ok_maint.astype(jnp.int32)),
        "occ_sum": xp.sum(occ2),
        "poison_sum": xp.sum(poi3),
        "stale_buckets": xp.sum(
            xp.where(swept_alive[:, None], stale.astype(jnp.int32), 0)),
        "model_err": xp.sum(
            xp.where(swept_alive[:, None],
                     xp.abs(analytic_at_sweep - sweep_fill), 0)),
    }
    return new_state, metrics


_jit_cache: dict = {}


def swarm_step(state, now, leave_rate, join_rate, loss, repub_rate,
               stale_age, reach, poison_on, poison_mask,
               poison_pressure, repub_on, sweep_idx, rand_node,
               rand_key):
    """One device launch advancing the whole swarm one tick (see module
    docstring).  All args are arrays/scalars; random bits are
    pre-drawn so :func:`swarm_step_host` is bit-identical."""
    import jax
    fn = _jit_cache.get("step")
    if fn is None:
        fn = _jit_cache["step"] = jax.jit(_swarm_step_impl)
    return fn(state, now, leave_rate, join_rate, loss, repub_rate,
              stale_age, reach, poison_on, poison_mask,
              poison_pressure, repub_on, sweep_idx, rand_node, rand_key)


# ================================================================== host
def _host_buckets(ids_bits, i):
    """Bucket index of every id relative to row ``i`` (first differing
    bit, clipped to 159; self reads 159 but callers mask self out) —
    the numpy mirror of radix.bucket_of."""
    x = ids_bits ^ ids_bits[i]
    anynz = x.any(axis=1)
    first = np.argmax(x, axis=1)
    cb = np.where(anynz, first, ID_BITS)
    return np.minimum(cb, ID_BITS - 1).astype(np.int64)


def swarm_step_host(state, now, leave_rate, join_rate, loss,
                    repub_rate, stale_age, reach, poison_on,
                    poison_mask, poison_pressure, repub_on, sweep_idx,
                    rand_node, rand_key):
    """Scalar-flavored numpy oracle, bit-identical to :func:`swarm_step`
    on the same pre-drawn random bits (pinned at small N in
    tests/test_swarm.py)."""
    xp = np
    ids = np.asarray(state["ids"], np.uint32)
    group = np.asarray(state["group"], np.int32)
    alive = np.asarray(state["alive"], bool)
    S = ids.shape[0]
    G = reach.shape[0]
    now = np.float32(now)
    leave_rate = np.float32(leave_rate)
    join_rate = np.float32(join_rate)
    loss = np.float32(loss)
    repub_rate = np.float32(repub_rate)
    stale_age = np.float32(stale_age)
    reach = np.asarray(reach, bool)
    sweep_idx = np.asarray(sweep_idx, np.int32)
    rand_node = np.asarray(rand_node, np.uint32)
    rand_key = np.asarray(rand_key, np.uint32)

    u0 = _unif(xp, rand_node[:, 0])
    u1 = _unif(xp, rand_node[:, 1])
    leave = alive & (u0 < leave_rate)
    join = (~alive) & (u1 < join_rate)
    alive2 = (alive & ~leave) | join
    last_seen2 = np.where(alive2, now,
                          np.asarray(state["last_seen"], np.float32))

    gcount = np.zeros((G,), np.int32)
    np.add.at(gcount, group, alive2.astype(np.int32))
    reach_i = reach.astype(np.int32)
    rc_group = np.sum(reach_i * gcount[None, :], axis=1, dtype=np.int32)
    self_reach = np.diagonal(reach)[group]
    rc = rc_group[group] - (alive2 & self_reach).astype(np.int32)
    n_alive = np.int32(alive2.astype(np.int32).sum())

    denom = np.float32(max(int(n_alive) - 1, 1))
    p_maint = (np.float32(1.0) - loss) * (rc.astype(np.float32) / denom)
    ok_maint = alive2 & (_unif(xp, rand_node[:, 2]) < p_maint)

    # maintenance_sweep mirror over the sample
    ids_bits = np.unpackbits(
        ids_to_bytes(ids).astype(np.uint8), axis=-1)        # [S, 160]
    M = sweep_idx.shape[0]
    counts = np.zeros((M, ID_BITS), np.int32)
    stale = np.zeros((M, ID_BITS), bool)
    probes = np.arange(ID_BITS)
    for m, i in enumerate(sweep_idx):
        valid_i = (alive2 & reach[group[i], group]
                   & (np.arange(S) != i))
        b = _host_buckets(ids_bits, i)
        bm = np.where(valid_i, b, -1)
        hit = bm[None, :] == probes[:, None]
        counts[m] = hit.sum(axis=1)
        vals = np.where(valid_i & (last_seen2 > 0), last_seen2,
                        -np.inf).astype(np.float32)
        last = np.max(np.where(hit, vals[None, :], -np.inf),
                      axis=1).astype(np.float32)
        stale[m] = (counts[m] > 0) & (last < now - stale_age)

    occ_n = _unpack_occ(xp, np.asarray(state["occ"], np.uint32))
    poi_n = _unpack_occ(xp, np.asarray(state["poison"], np.uint32))
    victim = bool(poison_on) & np.asarray(poison_mask, bool)
    poi2 = np.where(victim[:, None],
                    np.minimum(poi_n + int(poison_pressure),
                               np.maximum(K_BUCKET - occ_n, 0)),
                    poi_n)
    poi3 = np.where((ok_maint & ~victim)[:, None], 0, poi2)
    av = _avail(xp, rc)
    target = np.minimum(av, K_BUCKET - poi3)
    occ2 = np.where(ok_maint[:, None], target, occ_n)
    sweep_fill = np.minimum(counts, K_BUCKET - poi3[sweep_idx])
    sweep_fill = np.where(alive2[sweep_idx][:, None], sweep_fill, 0)
    occ2[sweep_idx] = sweep_fill
    occ2 = np.where(join[:, None], np.minimum(av, 1), occ2)
    occ2 = np.where(alive2[:, None], occ2, 0)
    poi3 = np.where(alive2[:, None] & ~join[:, None], poi3, 0)

    fresh = np.where(ok_maint | join, now,
                     np.asarray(state["table_fresh"], np.float32))
    fresh[sweep_idx] = np.where(alive2[sweep_idx], now,
                                fresh[sweep_idx]).astype(np.float32)

    replicas = np.asarray(state["replicas"], np.int32)
    keys = np.asarray(state["keys"], np.uint32)
    key_src = np.asarray(state["key_src"], np.int32)
    R = replicas.shape[1]
    if bool(repub_on):
        due = _unif(xp, rand_key) < repub_rate
        valid_ks = (alive2[None, :]
                    & reach[group[key_src][:, None], group[None, :]])
        sel, sel_valid = _closest_r(xp, keys, ids, valid_ks, R)
        newrep = np.where(sel_valid, sel, -1).astype(np.int32)
        replicas2 = np.where(due[:, None], newrep, replicas)
    else:
        replicas2 = replicas

    new_state = {
        "ids": ids, "group": group, "alive": alive2,
        "last_seen": last_seen2.astype(np.float32),
        "table_fresh": fresh.astype(np.float32),
        "occ": _pack_occ(xp, occ2), "poison": _pack_occ(xp, poi3),
        "keys": keys, "key_src": key_src,
        "replicas": replicas2.astype(np.int32),
    }
    analytic_at_sweep = target[sweep_idx]
    swept_alive = alive2[sweep_idx]
    metrics = {
        "n_alive": int(n_alive),
        "n_leave": int(leave.sum()),
        "n_join": int(join.sum()),
        "n_maint_ok": int(ok_maint.sum()),
        "occ_sum": int(occ2.sum()),
        "poison_sum": int(poi3.sum()),
        "stale_buckets": int(
            np.where(swept_alive[:, None], stale.astype(np.int32),
                     0).sum()),
        "model_err": int(
            np.where(swept_alive[:, None],
                     np.abs(analytic_at_sweep - sweep_fill), 0).sum()),
    }
    return new_state, metrics


# ================================================================ probes
def _lookup_probe_impl(ids, group, alive, occ, reach, probe_keys, src,
                       replicas):
    import jax.numpy as jnp
    from .ids import common_bits

    xp = jnp
    S = ids.shape[0]
    g_src = xp.take(group, src)
    # a lookup finds the value iff some ASSIGNED replica of the key is
    # alive and reachable from the source's side of any partition
    rep = xp.clip(replicas, 0, S - 1)
    rep_ok = (replicas >= 0) & xp.take(alive, rep)
    any_rep = xp.any(rep_ok & reach[g_src[:, None], xp.take(group, rep)],
                     axis=1)

    src_ids = xp.take(ids, src, axis=0)
    b = xp.minimum(common_bits(src_ids, probe_keys), ID_BITS - 1)
    cb_all = common_bits(src_ids[:, None, :], ids[None, :, :])
    bucket_all = xp.minimum(cb_all, ID_BITS - 1)
    inb = ((bucket_all == b[:, None]) & alive[None, :]
           & reach[g_src[:, None], group[None, :]]
           & (xp.arange(S, dtype=jnp.int32)[None, :] != src[:, None]))
    live_b = xp.sum(inb.astype(jnp.int32), axis=1)
    occ_n = _unpack_occ(xp, xp.take(occ, src, axis=0))
    occ_b = xp.take_along_axis(occ_n, b[:, None], axis=1)[:, 0]
    eff = xp.minimum(occ_b, live_b)
    total_occ = xp.sum(occ_n, axis=1)
    routing_ok = xp.where(live_b > 0, eff > 0, total_occ > 0)
    return xp.take(alive, src) & routing_ok & any_rep


def lookup_success_probe(state, reach, probe_keys, src, replicas):
    """Batched structural lookup-success probe (see module docstring).
    Returns bool [P]; one launch for the whole probe set — the swarm
    analogue of the PR-9 batched replica-coverage probe's one
    ``find_closest`` launch."""
    import jax
    fn = _jit_cache.get("probe")
    if fn is None:
        fn = _jit_cache["probe"] = jax.jit(_lookup_probe_impl)
    return fn(state["ids"], state["group"], state["alive"],
              state["occ"], reach, probe_keys, src, replicas)


def lookup_success_probe_host(state, reach, probe_keys, src, replicas):
    """numpy mirror of :func:`lookup_success_probe` (oracle pin)."""
    ids = np.asarray(state["ids"], np.uint32)
    group = np.asarray(state["group"], np.int32)
    alive = np.asarray(state["alive"], bool)
    reach = np.asarray(reach, bool)
    probe_keys = np.asarray(probe_keys, np.uint32)
    src = np.asarray(src, np.int32)
    replicas = np.asarray(replicas, np.int32)
    S = ids.shape[0]
    g_src = group[src]
    rep = np.clip(replicas, 0, S - 1)
    rep_ok = (replicas >= 0) & alive[rep]
    any_rep = np.any(rep_ok & reach[g_src[:, None], group[rep]], axis=1)

    ids_bits = np.unpackbits(ids_to_bytes(ids).astype(np.uint8), axis=-1)
    key_bits = np.unpackbits(ids_to_bytes(probe_keys).astype(np.uint8),
                             axis=-1)
    out = np.zeros((len(src),), bool)
    for p, s in enumerate(src):
        xk = ids_bits[s] ^ key_bits[p]
        b = min(int(np.argmax(xk)) if xk.any() else ID_BITS,
                ID_BITS - 1)
        buckets = _host_buckets(ids_bits, s)
        inb = ((buckets == b) & alive & reach[g_src[p], group]
               & (np.arange(S) != s))
        live_b = int(inb.sum())
        occ_n = _unpack_occ(np, np.asarray(state["occ"], np.uint32)[s])
        eff = min(int(occ_n[b]), live_b)
        routing_ok = (eff > 0) if live_b > 0 else (int(occ_n.sum()) > 0)
        out[p] = bool(alive[s]) and routing_ok and bool(any_rep[p])
    return out


def replica_coverage(state):
    """Per-key fraction of the key's TRUE closest-R alive nodes that
    are in its current replica assignment — the PR-9 replica-coverage
    invariant's structural form (the probe there cross-checks the true
    closest-8 against the live stores).  A partition skews assignments
    to one side, so coverage drops the moment the network heals and
    the true closest set is global again; republish restores it.
    float [K] in [0, 1]; integer set work only."""
    rep = np.asarray(state["replicas"], np.int32)
    alive = np.asarray(state["alive"], bool)
    ids = np.asarray(state["ids"], np.uint32)
    keys = np.asarray(state["keys"], np.uint32)
    sel, sel_valid = _closest_r(np, keys, ids, alive, rep.shape[1])
    hit = (sel[:, :, None] == rep[:, None, :]).any(axis=2) & sel_valid
    denom = np.maximum(sel_valid.sum(axis=1), 1)
    return hit.sum(axis=1) / denom


# ================================================================ driver
def init_swarm(seed: int, n_nodes: int, n_keys: int = 64, *,
               replicas: int = REPLICAS, n_groups: int = 2) -> Dict:
    """Build a converged swarm (host arrays; move to device with
    jnp.asarray via :class:`SwarmSim`).  Groups are balanced index
    ranges ``g0..g{G-1}`` — the names :class:`~opendht_tpu.chaos.
    Partition`/:class:`~opendht_tpu.chaos.Poison` phases refer to."""
    import jax

    kid, kkey = jax.random.split(jax.random.PRNGKey(seed))
    ids = np.asarray(jax.random.bits(kid, (n_nodes, N_LIMBS), np.uint32))
    keys = np.asarray(jax.random.bits(kkey, (n_keys, N_LIMBS), np.uint32))
    group = ((np.arange(n_nodes, dtype=np.int64) * n_groups)
             // n_nodes).astype(np.int32)
    alive = np.ones((n_nodes,), bool)
    rc = np.full((n_nodes,), n_nodes - 1, np.int32)
    occ = _pack_occ(np, _avail(np, rc))
    key_src = (np.arange(n_keys, dtype=np.int64) % n_nodes).astype(np.int32)
    state = {
        "ids": ids, "group": group, "alive": alive,
        "last_seen": np.zeros((n_nodes,), np.float32),
        "table_fresh": np.zeros((n_nodes,), np.float32),
        "occ": occ,
        "poison": np.zeros((n_nodes, OCC_LIMBS), np.uint32),
        "keys": keys, "key_src": key_src,
        "replicas": np.full((n_keys, replicas), -1, np.int32),
    }
    # initial replica assignment: closest-R over the full population
    sel, sel_valid = _closest_r(np, keys, ids, alive, replicas)
    state["replicas"] = np.where(sel_valid, sel, -1).astype(np.int32)
    return state


def params_at(plan: chaos.FaultPlan, rel: float, n_groups: int,
              group: np.ndarray) -> Dict:
    """Fold the plan's phases active at relative time ``rel`` into the
    stepper's tick parameters: storm rates, wildcard loss, the [G,G]
    reachability matrix (partitions reference groups ``g0..``;
    healing = the phase window ends), and the poison mask/pressure."""
    storm = plan.storm_at(rel) or chaos.Storm()
    loss = 0.0
    for ph in plan.phases_at(rel):
        for rule in ph.rules:
            if rule.src == chaos.ANY and rule.dst == chaos.ANY:
                loss = 1.0 - (1.0 - loss) * (1.0 - rule.loss)
    names = ["g%d" % i for i in range(n_groups)]
    reach = np.ones((n_groups, n_groups), bool)
    for _pname, part in plan.partitions_at(rel):
        for i, a in enumerate(names):
            for j, b in enumerate(names):
                if part.blocks(a, b):
                    reach[i, j] = False
    poison = plan.poison_at(rel)
    if poison is not None and poison.victim in names:
        vidx = names.index(poison.victim)
        poison_mask = np.asarray(group) == vidx
        poison_on = True
        pressure = int(poison.per_bucket)
    else:
        poison_mask = np.zeros((len(group),), bool)
        poison_on = False
        pressure = 0
    return {
        "leave_rate": np.float32(storm.leave_rate),
        "join_rate": np.float32(storm.join_rate),
        "loss": np.float32(loss),
        "reach": reach,
        "poison_on": bool(poison_on),
        "poison_mask": poison_mask,
        "poison_pressure": np.int32(pressure),
    }


class SwarmSim:
    """Host driver: advances a device-resident swarm through a
    FaultPlan, one :func:`swarm_step` launch per tick, publishing
    ``dht_swarm_*`` gauges and ``chaos_phase``/``swarm_verdict`` flight
    events on the PR-3/PR-9 spine so swarm verdicts ride the same
    health-invariant and timeline machinery as live clusters."""

    def __init__(self, plan: chaos.FaultPlan, *, n_nodes: int,
                 n_keys: int = 64, n_groups: int = 2, seed: int = 7,
                 tick_dt: float = 1.0, sweep_sample: int = 32,
                 repub_every: int = 4, repub_rate: float = 1.0,
                 stale_age: float = 5.0, device: bool = True):
        import jax
        self.plan = plan
        self.n_groups = n_groups
        self.tick_dt = tick_dt
        self.sweep_sample = min(sweep_sample, n_nodes)
        self.repub_every = repub_every
        self.repub_rate = repub_rate
        self.stale_age = stale_age
        self.device = device
        self.t = 0.0
        self.tick_no = 0
        self._key = jax.random.PRNGKey(seed)
        host = init_swarm(seed, n_nodes, n_keys, n_groups=n_groups)
        self._group_host = host["group"]
        if device:
            import jax.numpy as jnp
            self.state = {k: jnp.asarray(v) for k, v in host.items()}
        else:
            self.state = host
        self._verdict = HEALTHY
        self._phase_names: tuple = ()
        reg = telemetry.get_registry()
        self._g = {name: reg.gauge("dht_swarm_" + name)
                   for name in ("alive", "lookup_success",
                                "replica_coverage", "poison_occupancy",
                                "occupancy", "model_err")}
        self._tracer = tracing.get_tracer()

    # -- one stepper launch per tick --------------------------------------
    def tick(self) -> Dict:
        import jax
        import jax.numpy as jnp
        rel = self.t
        p = params_at(self.plan, rel, self.n_groups, self._group_host)
        self._note_phases(rel)
        self._key, k1, k2 = jax.random.split(self._key, 3)
        S = self._group_host.shape[0]
        K = np.asarray(self.state["keys"]).shape[0]
        rand_node = jax.random.bits(k1, (S, 3), jnp.uint32)
        rand_key = jax.random.bits(k2, (K,), jnp.uint32)
        M = self.sweep_sample
        sweep_idx = ((np.arange(M, dtype=np.int64) + self.tick_no * M)
                     % S).astype(np.int32)
        repub_on = (self.tick_no % self.repub_every) == 0
        now = np.float32(rel + self.tick_dt)
        step = swarm_step if self.device else swarm_step_host
        rn = rand_node if self.device else np.asarray(rand_node)
        rk = rand_key if self.device else np.asarray(rand_key)
        self.state, metrics = step(
            self.state, now, p["leave_rate"], p["join_rate"], p["loss"],
            np.float32(self.repub_rate), np.float32(self.stale_age),
            p["reach"], p["poison_on"], p["poison_mask"],
            p["poison_pressure"], repub_on, sweep_idx, rn, rk)
        self.t += self.tick_dt
        self.tick_no += 1
        metrics = {k: int(v) for k, v in metrics.items()}
        self._g["alive"].set(metrics["n_alive"])
        self._g["poison_occupancy"].set(metrics["poison_sum"])
        # ISSUE-15 satellite: total replica-slot occupancy per tick —
        # the stepper computed occ_sum from day one but published only
        # the poison slice, so a swarm soak's history frames carried no
        # storage-pressure series to bundle at incident time
        self._g["occupancy"].set(metrics["occ_sum"])
        self._g["model_err"].set(metrics["model_err"])
        return metrics

    def _note_phases(self, rel: float) -> None:
        names = tuple(ph.name for ph in self.plan.phases_at(rel))
        if names != self._phase_names:
            if self._tracer.enabled:
                self._tracer.event("chaos_phase", active=",".join(names)
                                   or "(none)", t=rel)
            self._phase_names = names

    # -- invariants --------------------------------------------------------
    def probe(self, n_probes: int = 32) -> Dict:
        """Lookup-success + replica-coverage invariants at the current
        tick, rolled into a healthy|degraded|unhealthy verdict (the
        PR-9 thresholds: unhealthy < 0.5, degraded < 0.9)."""
        import jax.numpy as jnp
        keys = np.asarray(self.state["keys"])
        P = min(n_probes, keys.shape[0])
        probe_keys = keys[:P]
        rep = np.asarray(self.state["replicas"])[:P]
        # lookups originate at ALIVE nodes (a dead source is not a
        # failed lookup, it is no lookup) — deterministic stride sample
        live = np.nonzero(np.asarray(self.state["alive"]))[0]
        if len(live) == 0:
            return {"lookup_success": 0.0, "replica_coverage": 0.0,
                    "verdict": UNHEALTHY}
        src = live[((np.arange(P, dtype=np.int64) * 997 + self.tick_no)
                    % len(live))].astype(np.int32)
        rel = self.t
        p = params_at(self.plan, rel, self.n_groups, self._group_host)
        if self.device:
            ok = np.asarray(lookup_success_probe(
                self.state, jnp.asarray(p["reach"]),
                jnp.asarray(probe_keys), jnp.asarray(src),
                jnp.asarray(rep)))
        else:
            ok = lookup_success_probe_host(self.state, p["reach"],
                                           probe_keys, src, rep)
        cov = replica_coverage(self.state)
        success = float(ok.sum()) / max(len(ok), 1)
        coverage = float(cov.mean()) if len(cov) else 1.0
        worst = min(success, coverage)
        verdict = (UNHEALTHY if worst < 0.5
                   else DEGRADED if worst < 0.9 else HEALTHY)
        self._g["lookup_success"].set(success)
        self._g["replica_coverage"].set(coverage)
        if verdict != self._verdict:
            if self._tracer.enabled:
                self._tracer.event("swarm_verdict", to=verdict,
                                   frm=self._verdict,
                                   lookup_success=round(success, 4),
                                   coverage=round(coverage, 4))
            self._verdict = verdict
        return {"lookup_success": success, "replica_coverage": coverage,
                "verdict": verdict}

    def run(self, ticks: int, *, probe_every: int = 1) -> list:
        out = []
        for i in range(ticks):
            m = self.tick()
            if probe_every and (i % probe_every) == 0:
                m.update(self.probe())
            out.append(m)
        return out
