"""PHT — Prefix Hash Tree: a distributed trie over the DHT for prefix and
multi-dimensional (z-curve) indexing.

Behavioral port of the reference implementation (reference:
include/opendht/indexation/pht.h:49-533, src/indexation/pht.cpp):

- :class:`Prefix` — bit-string with optional per-bit "known" flags; node
  labels in the trie.  ``hash()`` = H(content ‖ size&0xFF) (pht.h:123-127).
- :class:`Cache` — local trie of recently-seen PHT nodes with 5-minute
  expiry, used to pick a good starting depth (pht.cpp:61-146).
- :class:`IndexEntry` — {prefix, (hash, value-id)} payload stored at leaf
  nodes, tagged by ``user_type`` (pht.h:267-286).
- :class:`Pht` — ``lookup`` does a binary search over prefix lengths,
  probing "canary" values that mark live trie nodes (pht.cpp:150-297);
  ``insert`` walks to the leaf, splits when a node holds
  MAX_NODE_ENTRY_COUNT entries (pht.cpp:330-378,516-528), refreshes
  canaries up the path (pht.cpp:299-328), and re-inserts deeper when a
  leaf later splits (checkPhtUpdate, pht.cpp:487-514).
- multi-field keys are linearized by bit-interleaving (z-curve) padded
  fields (pht.cpp:380-456).
"""

from __future__ import annotations

import logging
import random
import time as _time
from typing import Callable, Dict, List, Optional, Tuple

from ..infohash import InfoHash
from ..core.value import Value
from ..utils import pack_msg, unpack_msg

log = logging.getLogger("opendht_tpu.pht")

MAX_NODE_ENTRY_COUNT = 16          # pht.h:297
CACHE_MAX_ELEMENT = 1024           # pht.h:383
CACHE_NODE_EXPIRE_TIME = 5 * 60.0  # pht.h:384
INDEX_PREFIX = "index.pht."        # pht.h:292
USER_DATA_EXPIRATION = 10 * 60.0   # IndexEntry::TYPE = USER_DATA


class Prefix:
    """A trie-node label: ``size`` bits of ``content`` with optional
    per-bit flags (0 bit = "unknown", used by z-curve keys)."""

    __slots__ = ("size", "content", "flags")

    def __init__(self, content: bytes = b"", flags: bytes = b"",
                 size: Optional[int] = None):
        self.content = bytes(content)
        self.flags = bytes(flags)
        self.size = len(self.content) * 8 if size is None else int(size)

    @classmethod
    def from_hash(cls, h: InfoHash) -> "Prefix":
        return cls(bytes(h))

    # -- accessors ---------------------------------------------------------
    def _bit(self, blob: bytes, pos: int) -> bool:
        if pos >= len(self.content) * 8:
            raise IndexError("pos larger than prefix size")
        return bool((blob[pos // 8] >> (7 - (pos % 8))) & 1)

    def is_content_bit_active(self, pos: int) -> bool:
        return self._bit(self.content, pos)

    def is_flag_active(self, pos: int) -> bool:
        """Unknown-flag check; empty flags = everything known
        (pht.h:93-100; note the reference indexes flags per *byte* in
        common_bits — we keep that behavior there)."""
        return not self.flags or self._bit(self.flags, pos)

    # -- derivation --------------------------------------------------------
    def get_prefix(self, length: int) -> "Prefix":
        """First ``length`` bits (negative = size + length)
        (pht.h:70-89)."""
        if length < 0:
            length += self.size
        if length < 0 or length > len(self.content) * 8:
            raise IndexError("len larger than prefix size")
        nbytes, rem = length // 8, length % 8
        content = bytearray(self.content[:nbytes])
        flags = bytearray(self.flags[:nbytes]) if self.flags else bytearray()
        if rem:
            content.append(self.content[nbytes] & (0xFF << (8 - rem)))
            if self.flags:
                flags.append(self.flags[nbytes] & (0xFF << (8 - rem)))
        return Prefix(bytes(content), bytes(flags), length)

    def get_full_size(self) -> "Prefix":
        return Prefix(self.content, self.flags, len(self.content) * 8)

    def get_sibling(self) -> "Prefix":
        """Same label with the last bit swapped (pht.h:111-121)."""
        p = Prefix(self.content, self.flags, self.size)
        if self.size:
            p.swap_content_bit(self.size - 1)
        return p

    def swap_content_bit(self, bit: int) -> None:
        """Flip bit ``bit`` in the MSB-first numbering used everywhere
        else here.  (The reference's swapBit (pht.h:252-259) uses an
        off-by-one convention internally inconsistent with its own
        isActiveBit; we keep one consistent numbering instead.)"""
        b = bytearray(self.content)
        if bit >= len(b) * 8:
            raise IndexError("bit larger than prefix size")
        b[bit // 8] ^= 1 << (7 - bit % 8)
        self.content = bytes(b)

    def add_padding_content(self, size: int) -> None:
        """Zero-pad to ``size`` bytes, marking the first pad bit so padded
        keys of different lengths stay distinct (pht.h:215-227)."""
        b = bytearray(self.content)
        while len(b) < size:
            b.append(0)
        if self.size < len(b) * 8:
            b[self.size // 8] ^= 1 << (7 - self.size % 8)
        self.content = bytes(b)

    def update_flags(self) -> None:
        """Mark the first ``size`` bits known, the padding unknown
        (pht.h:185-199)."""
        flags = bytearray(self.flags)
        csize = self.size - len(flags) * 8
        while csize >= 8:
            flags.append(0xFF)
            csize -= 8
        if csize > 0:
            flags.append((0xFF << (8 - csize)) & 0xFF)
        while len(flags) < len(self.content):
            flags.append(0xFF)
        self.flags = bytes(flags)

    # -- hashing / compare -------------------------------------------------
    def hash(self) -> InfoHash:
        """DHT key of this trie node (pht.h:123-127)."""
        return InfoHash.get(self.content + bytes([self.size & 0xFF]))

    @staticmethod
    def common_bits(p1: "Prefix", p2: "Prefix") -> int:
        """Longest common prefix in bits, never exceeding either size
        (pht.h:129-162; the reference mixes bit/byte units here — this is
        the corrected semantics, only used for inexact-match ranking)."""
        longest_bits = min(p1.size, p2.size)
        nbytes = min(len(p1.content), len(p2.content),
                     (longest_bits + 7) // 8)
        i = 0
        while i < nbytes:
            if (p1.content[i] != p2.content[i]
                    or not p1.is_flag_active(i)
                    or not p2.is_flag_active(i)):
                break
            i += 1
        if i == nbytes:
            return longest_bits
        x = p1.content[i] ^ p2.content[i]
        if x == 0:
            return min(8 * i, longest_bits)   # flag, not content, differed
        j = 0
        while not (x & 0x80):
            x = (x << 1) & 0xFF
            j += 1
        return min(8 * i + j, longest_bits)

    def __eq__(self, other):
        return (isinstance(other, Prefix) and self.size == other.size
                and self.content == other.content)

    def __hash__(self):
        return hash((self.size, self.content))

    def to_string(self) -> str:
        bits = "".join(
            str(int(self.is_content_bit_active(i))) for i in range(self.size))
        return f"Prefix({bits})"

    __repr__ = to_string


class _CacheNode:
    __slots__ = ("last_reply", "parent", "children")

    def __init__(self, parent=None):
        self.last_reply = 0.0
        self.parent = parent
        self.children: Dict[bool, "_CacheNode"] = {}


class Cache:
    """Local trie of recently-confirmed PHT nodes (pht.cpp:61-146)."""

    def __init__(self, clock: Callable[[], float] = _time.monotonic):
        self._clock = clock
        self._root: Optional[_CacheNode] = None
        self._leaves: List[Tuple[float, _CacheNode]] = []

    def _expire(self, now: float, max_extra: int = 0) -> None:
        while self._leaves and (
                self._leaves[0][0] + CACHE_NODE_EXPIRE_TIME < now
                or len(self._leaves) > CACHE_MAX_ELEMENT - max_extra):
            _, leaf = self._leaves.pop(0)
            # prune the branch upward while childless
            node = leaf
            while node is not None and not node.children:
                parent = node.parent
                if parent is not None:
                    for k, v in list(parent.children.items()):
                        if v is node:
                            del parent.children[k]
                elif node is self._root:
                    self._root = None
                node = parent

    def insert(self, p: Prefix) -> None:
        now = self._clock()
        self._expire(now, max_extra=1)
        if self._root is None:
            self._root = _CacheNode()
        node = self._root
        node.last_reply = now
        for i in range(p.size):
            bit = p.is_content_bit_active(i)
            child = node.children.get(bit)
            if child is None:
                child = _CacheNode(parent=node)
                node.children[bit] = child
            node = child
            node.last_reply = now
        self._leaves.append((now, node))

    def lookup(self, p: Prefix) -> int:
        """Deepest known depth along ``p``; -1 when nothing cached
        (pht.cpp:110-146)."""
        now = self._clock()
        self._expire(now)
        pos = -1
        node = self._root
        last: Optional[_CacheNode] = None
        while node is not None:
            pos += 1
            if pos >= len(p.content) * 8:
                break
            last = node
            node.last_reply = now
            node = node.children.get(p.is_content_bit_active(pos))
        if pos >= 0 and last is not None:
            self._leaves.append((now, last))
        return pos


class IndexEntry:
    """Leaf payload: the full linearized key + the indexed (hash, vid)
    (pht.h:267-286)."""

    __slots__ = ("prefix", "value", "name")

    def __init__(self, prefix: bytes = b"",
                 value: Tuple[InfoHash, int] = (InfoHash(), 0),
                 name: str = ""):
        self.prefix = bytes(prefix)
        self.value = (InfoHash(value[0]), int(value[1]))
        self.name = name

    def pack(self) -> Value:
        v = Value(pack_msg({"prefix": self.prefix,
                            "value": [bytes(self.value[0]), self.value[1]]}))
        v.user_type = self.name
        # deterministic id: re-inserting the same entry (e.g. after a leaf
        # split) refreshes the stored value instead of accumulating
        # duplicates (the reference leaves random ids and relies on value
        # expiry; dedup keeps hot trie nodes small)
        digest = InfoHash.get(self.prefix + bytes(self.value[0])
                              + self.value[1].to_bytes(8, "big"))
        v.id = int.from_bytes(bytes(digest)[:8], "big") or 1
        return v

    @classmethod
    def unpack(cls, v: Value) -> "IndexEntry":
        m = unpack_msg(v.data)
        h, vid = m["value"][0], m["value"][1]
        return cls(bytes(m["prefix"]), (InfoHash(bytes(h)), int(vid)),
                   v.user_type)


class Pht:
    """A named distributed prefix-hash-tree index over a DhtRunner-like
    node (anything with get/put/listen/cancel_listen)."""

    def __init__(self, name: str, key_spec: Dict[str, int], dht,
                 rng: Optional[random.Random] = None):
        self.name = INDEX_PREFIX + name
        self.canary = self.name + ".canary"
        self.key_spec = dict(key_spec)
        self.dht = dht
        self.cache = Cache()
        self._rng = rng or random.Random()

    # ------------------------------------------------------------- keys
    def valid_key(self, key: Dict[str, bytes]) -> bool:
        """(pht.h:508-517)"""
        if set(key) != set(self.key_spec):
            return False
        return all(len(v) <= self.key_spec[k] for k, v in key.items())

    def linearize(self, key: Dict[str, bytes]) -> Prefix:
        """Pad each field to max-spec+1 bytes, mark pad bits unknown,
        z-curve interleave (pht.cpp:433-456)."""
        if not self.valid_key(key):
            raise ValueError("Key does not match the PHT key spec.")
        max_len = max(self.key_spec.values()) + 1
        parts = []
        for field in sorted(key):                 # Key is an ordered map
            p = Prefix(key[field])
            p.add_padding_content(max_len)
            p.update_flags()
            parts.append(p)
        return self.zcurve(parts)

    @staticmethod
    def zcurve(parts: List[Prefix]) -> Prefix:
        """Bit-interleave contents and flags of equal-size prefixes
        (pht.cpp:380-431)."""
        if len(parts) == 1:
            return parts[0]
        nbits = len(parts[0].content) * 8
        content = bytearray((nbits * len(parts) + 7) // 8)
        flags = bytearray(len(content))
        out = 0
        for i in range(nbits):
            for p in parts:
                if p.is_content_bit_active(i):
                    content[out // 8] |= 1 << (7 - out % 8)
                if p._bit(p.flags, i):
                    flags[out // 8] |= 1 << (7 - out % 8)
                out += 1
        return Prefix(bytes(content), bytes(flags), out)

    # ------------------------------------------------------------ lookup
    def _pht_filter(self, v: Value) -> bool:
        return v.user_type.startswith(self.name)

    def lookup(self, key: Dict[str, bytes], cb=None, done_cb=None,
               exact_match: bool = True) -> None:
        """Find the leaf for ``key``; cb(values, prefix) once found
        (pht.cpp:299-327)."""
        prefix = self.linearize(key)
        state = {"lo": 0, "hi": prefix.size,
                 "max_common": 0 if not exact_match else None}
        vals: List[IndexEntry] = []

        def on_leaf(entries: List[IndexEntry], p: Prefix):
            if cb:
                cb([e.value for e in entries], p)

        self._lookup_step(prefix, state, vals, on_leaf, done_cb,
                          start=self.cache.lookup(prefix))

    def _lookup_step(self, p: Prefix, state: dict, vals: List[IndexEntry],
                     cb, done_cb, start: int = -1,
                     all_values: bool = False) -> None:
        """One binary-search step: probe depth mid and mid+1 for canaries
        (pht.cpp:150-297)."""
        lo, hi = state["lo"], state["hi"]
        if lo > hi:
            if done_cb:
                done_cb(True)
            return
        mid = start if start >= 0 else (lo + hi) // 2
        first = {"done": False, "is_pht": False, "ok": True}
        second = {"done": False, "is_pht": False, "ok": True}
        if mid >= p.size - 1:
            second["done"] = True

        def on_value(v: Value, res: dict) -> None:
            if v.user_type == self.canary:
                res["is_pht"] = True
                return
            try:
                entry = IndexEntry.unpack(v)
            except Exception:
                return
            if any(e.value == entry.value for e in vals):
                return
            if state["max_common"] is not None:    # inexact match
                common = Prefix.common_bits(p, Prefix(entry.prefix))
                if not vals or common > state["max_common"]:
                    vals.clear()
                    vals.append(entry)
                    state["max_common"] = common
                elif common == state["max_common"]:
                    vals.append(entry)
            elif all_values or entry.prefix == p.content:
                vals.append(entry)

        def on_done():
            if not (first["ok"] and second["ok"]):
                if done_cb:
                    done_cb(False)
                return
            is_leaf = first["is_pht"] and not second["is_pht"]
            if is_leaf or state["lo"] > state["hi"]:
                to_insert = p.get_prefix(mid)
                self.cache.insert(to_insert)
                if cb:
                    if (not vals and state["max_common"] is not None
                            and mid > 0):
                        # inexact: descend the sibling subtree
                        sibling = p.get_prefix(mid).get_sibling() \
                                   .get_full_size()
                        state["lo"] = mid
                        state["hi"] = sibling.size
                        self._lookup_step(sibling, state, vals, cb,
                                          done_cb, all_values=all_values)
                    cb(vals, to_insert)
                if done_cb:
                    done_cb(True)
            elif first["is_pht"]:
                state["lo"] = mid + 1
                self._lookup_step(p, state, vals, cb, done_cb,
                                  all_values=all_values)
            else:
                if done_cb:
                    done_cb(False)

        def get_done_first(ok, _nodes=None):
            if not ok:
                first["done"] = True
                first["ok"] = False
                if second["done"]:
                    on_done()
                return
            if not first["is_pht"]:
                # not a PHT node: go shallower; the second probe is
                # abandoned (its completion must not fire on_done, so
                # first stays not-done — pht.cpp:252-262)
                state["hi"] = mid - 1
                self._lookup_step(p, state, vals, cb, done_cb,
                                  all_values=all_values)
            else:
                first["done"] = True
                if second["done"]:
                    on_done()

        def get_done_second(ok, _nodes=None):
            second["done"] = True
            if not ok:
                second["ok"] = False
            if first["done"]:
                on_done()

        def on_values(res):
            def cb(values: List[Value]) -> bool:
                for v in values:
                    on_value(v, res)
                return True
            return cb

        self.dht.get(p.get_prefix(mid).hash(), on_values(first),
                     get_done_first, self._pht_filter)
        if mid < p.size - 1:
            self.dht.get(p.get_prefix(mid + 1).hash(), on_values(second),
                         get_done_second, self._pht_filter)

    # ------------------------------------------------------------ insert
    def insert(self, key: Dict[str, bytes], value: Tuple[InfoHash, int],
               done_cb=None) -> None:
        """Index ``value`` under ``key`` (pht.h:346-360)."""
        p = self.linearize(key)
        entry = IndexEntry(p.content, value, self.name)
        self._insert(p, entry, {"lo": 0, "hi": p.size, "max_common": None},
                     _time.monotonic(), True, done_cb)

    def _insert(self, kp: Prefix, entry: IndexEntry, state: dict,
                time_p: float, check_split: bool, done_cb=None) -> None:
        """(pht.cpp:330-378)"""
        if time_p + USER_DATA_EXPIRATION < _time.monotonic():
            return
        vals: List[IndexEntry] = []
        final = {"prefix": None}

        def on_leaf(entries: List[IndexEntry], p: Prefix):
            final["prefix"] = p

        def real_insert(p: Prefix, e: IndexEntry):
            self.update_canary(p)
            self._check_pht_update(p, e, time_p)
            self.cache.insert(p)
            v = e.pack()
            self.dht.put(p.hash(), v,
                         (lambda ok, ns=None: done_cb(ok)) if done_cb
                         else None)

        def on_done(ok):
            if not ok:
                if done_cb:
                    done_cb(False)
                return
            fp = final["prefix"] or kp.get_prefix(0)
            if not check_split or fp.size == kp.size:
                real_insert(fp, entry)
            elif len(vals) < MAX_NODE_ENTRY_COUNT:
                self._get_real_prefix(fp, entry, real_insert)
            else:
                self._split(fp, vals, entry, real_insert)

        self._lookup_step(kp, state, vals, on_leaf, on_done,
                          start=self.cache.lookup(kp), all_values=True)

    def update_canary(self, p: Prefix) -> None:
        """Refresh this node's canary, its sibling's, and probabilistically
        the parents' (pht.cpp:299-328)."""
        # fixed id: repeated canary refreshes extend the same value's
        # lifetime instead of piling up distinct values at hot trie nodes
        v = Value(b"\xc0", value_id=1)
        v.user_type = self.canary

        def bubble(ok, _nodes=None):
            if p.size and self._rng.random() < 0.5:
                self.update_canary(p.get_prefix(-1))

        self.dht.put(p.hash(), v, bubble)
        if p.size:
            v2 = Value(b"\xc0", value_id=1)
            v2.user_type = self.canary
            self.dht.put(p.get_sibling().hash(), v2)

    def _get_real_prefix(self, p: Prefix, entry: IndexEntry,
                         end_cb) -> None:
        """Merge check: if parent+this+sibling hold < MAX entries, insert
        at the parent (pht.cpp:458-512)."""
        if p.size == 0:
            end_cb(p, entry)
            return
        parent = p.get_prefix(-1)
        counter = {"entries": 0, "ended": 0}

        def count(values: List[Value]) -> bool:
            counter["entries"] += sum(
                1 for v in values if v.user_type != self.canary)
            return True

        def on_done(ok, _nodes=None):
            counter["ended"] += 1
            if counter["ended"] == 3:
                if counter["entries"] < MAX_NODE_ENTRY_COUNT:
                    end_cb(parent, entry)
                else:
                    end_cb(p, entry)

        for target in (parent, p, p.get_sibling()):
            self.dht.get(target.hash(), count, on_done, self._pht_filter)

    def _check_pht_update(self, p: Prefix, entry: IndexEntry,
                          time_p: float) -> None:
        """Listen one level deeper: if a canary later appears there, the
        leaf split and our entry must be re-inserted deeper
        (pht.cpp:487-514)."""
        full = Prefix(entry.prefix)
        if p.size >= len(full.content) * 8:
            return
        next_prefix = full.get_prefix(p.size + 1)
        token_box = {}

        def on_values(values: List[Value], expired: bool = False) -> bool:
            if expired:
                return True
            for v in values:
                if v.user_type == self.canary:
                    self._insert(full, entry,
                                 {"lo": 0, "hi": full.size,
                                  "max_common": None},
                                 time_p, False, None)
                    tok = token_box.get("token")
                    if tok:
                        self.dht.cancel_listen(next_prefix.hash(), tok)
                    return False
            return True

        tok = self.dht.listen(next_prefix.hash(), on_values,
                              self._pht_filter)

        def record(t) -> None:
            # no live subscription: None = shed at ingest admission
            # (round 12 backpressure), 0 = the callback consumed local
            # values and stopped.  Either way the insert itself already
            # completed — only the split watch degrades, so record
            # nothing rather than a bogus token
            if t:
                token_box["token"] = t
            else:
                log.debug("pht: no split-watch subscription for %s (%s)",
                          next_prefix.to_string(),
                          "shed" if t is None else "satisfied locally")

        if hasattr(tok, "add_done_callback"):
            # DhtRunner backend: listen returns a Future resolving to
            # the runner token (0 = shed) — never block the insert path
            tok.add_done_callback(
                lambda f: record(0 if f.exception() else f.result()))
        else:
            record(tok)

    @staticmethod
    def find_split_location(compared: Prefix,
                            vals: List[IndexEntry]) -> int:
        """First bit where ``compared`` diverges from every stored entry
        (pht.h:482-489)."""
        for i in range(len(compared.content) * 8 - 1):
            for e in vals:
                if (Prefix(e.prefix).is_content_bit_active(i)
                        != compared.is_content_bit_active(i)):
                    return i + 1
        return len(compared.content) * 8 - 1

    def _split(self, insert: Prefix, vals: List[IndexEntry],
               entry: IndexEntry, end_cb) -> None:
        """(pht.cpp:516-528)"""
        full = Prefix(entry.prefix)
        loc = self.find_split_location(full, vals)
        prefix_to_insert = full.get_prefix(loc)
        while loc != insert.size - 1 and loc > 0:
            self.update_canary(full.get_prefix(loc))
            loc -= 1
        end_cb(prefix_to_insert, entry)
