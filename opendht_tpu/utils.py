"""Shared host-side primitives: time, msgpack helpers, exceptions, flags.

Counterpart of the reference's ``include/opendht/utils.h`` (steady
clock/time_point/duration utils.h:77-114, packMsg/unpackMsg :121-137,
DhtException/SocketException :63-73, WANT4/WANT6 :32-33).  Times here are
plain floats on the monotonic clock — the Python-idiomatic equivalent of
``std::chrono::steady_clock::time_point``.
"""

from __future__ import annotations

import random
import time as _time
from typing import Any

import msgpack

# A time_point far enough in the future to mean "never" (the reference
# uses time_point::max(); a finite sentinel keeps float math safe).
TIME_MAX = float("inf")

#: want flags for dual-stack requests (utils.h:32-33)
WANT4 = 1
WANT6 = 2


def now() -> float:
    """Monotonic 'steady clock' timestamp in seconds."""
    return _time.monotonic()


def wall_now() -> float:
    """Wall-clock timestamp (seconds since epoch) for value `created`
    dates, which cross the network (reference uses system_clock there)."""
    return _time.time()


def uniform_duration(low: float, high: float, rng: random.Random | None = None) -> float:
    """Random duration in [low, high] — jitter for maintenance schedules
    (utils.h:93-107 uniform_duration_distribution)."""
    r = rng.uniform(low, high) if rng is not None else random.uniform(low, high)
    return r


def lazy_module(name: str):
    """Import-on-first-attribute-touch module proxy.

    The crypto layer needs the ``cryptography`` wheel at IMPORT time
    (x509/serialization bindings), but the runner/SecureDht stack only
    touches it at CALL time — and only when an identity or certificate
    is actually in play.  Binding ``crypto = lazy_module(...)`` lets
    the whole runtime import and run identity-less in minimal
    containers (the PEP 562 package-level re-exports made the same
    move for kernels in round 6); the ImportError surfaces on first
    real use instead.
    """
    import importlib

    class _Lazy:
        def __getattr__(self, attr):
            # memoize on the proxy: __getattr__ only fires on misses,
            # so each attribute pays the importlib lookup exactly once
            # (the proxy sits on SecureDht's per-value hot paths)
            val = getattr(importlib.import_module(name), attr)
            setattr(self, attr, val)
            return val

        def __repr__(self):
            return f"<lazy module {name!r}>"

    return _Lazy()


class DhtException(Exception):
    """Base error for DHT operations (utils.h:63-67)."""


class SocketException(DhtException):
    """Network-level failure (utils.h:69-73)."""


def pack_msg(obj: Any) -> bytes:
    """msgpack-encode (packMsg, utils.h:121-126). use_bin_type=True maps
    Python bytes→bin and str→str, matching msgpack-c's defaults."""
    return msgpack.packb(obj, use_bin_type=True)


def unpack_msg(data: bytes) -> Any:
    """msgpack-decode (unpackMsg, utils.h:128-133). raw=False decodes
    str family to Python str; bin stays bytes."""
    return msgpack.unpackb(data, raw=False, strict_map_key=False)


def unpack_stream(data: bytes):
    """Iterate over concatenated msgpack objects (Unpacker feed)."""
    up = msgpack.Unpacker(raw=False, strict_map_key=False)
    up.feed(data)
    yield from up
