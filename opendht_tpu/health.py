"""Cluster health observatory: the declarative SLO engine + node verdict.

Five rounds of observability (round 8 telemetry, round 9 tracing/flight
recorder, round 11 kernel ledger) export raw signals; nothing
*interpreted* them — no health model, no SLO evaluation, no readiness
surface.  This module is the interpretation layer (the measurement half
of ROADMAP item 4's invariants, standing infrastructure the swarm
simulator plugs into):

- :class:`SloObjective` / :class:`HealthConfig` — a declarative per-op
  objective set (availability = fraction of ops with ``ok=true``;
  latency = fraction of ops under a threshold), configured through
  ``runtime/config.py`` (``Config.health``).
- :class:`HealthEvaluator` — multi-window **burn-rate** evaluation
  (Google SRE style): per objective, the error-budget burn rate —
  observed bad fraction / allowed bad fraction — is computed over a
  *fast* window (sudden total failure pages within seconds) and a
  *slow* window (a 2-3x budget leak that a fast window never sees).
  The evaluator reads ONLY the round-8 registry (log-bucket
  ``Histogram`` deltas, counters, gauges): each tick snapshots the
  cumulative series and windows are differences of snapshots — no new
  instrumentation on any hot path, no device work, kernels untouched.
- Derived per-node signals, thresholded ``ok | degraded | unhealthy``:
  ingest queue saturation vs ``ingest_queue_max`` (round 12 wave
  builder), scheduler tick lag (windowed p95 of
  ``dht_scheduler_tick_lag_seconds``), request timeout ratio
  (``dht_net_requests_expired_total`` / ``..._sent_total`` deltas),
  stale-bucket fraction from the round-10 ``maintenance_sweep``
  outputs, and node connectivity.
- One rolled-up verdict ``healthy | degraded | unhealthy`` with
  per-signal attribution and **hysteresis** (a tripped objective clears
  only below ``recover_ratio`` x its threshold, so a boundary value
  cannot flap the verdict).  Zero traffic / empty registry reports
  *healthy-unknown* — absence of evidence is not an outage.
- Evaluated on a periodic scheduler tick (``runtime/runner.py`` attaches
  :class:`NodeHealth`), emitting ``health_transition`` /
  ``slo_violation`` flight-recorder events (round-9 ring) so every
  degradation is trace-correlatable, and ``dht_health_*`` /
  ``dht_slo_*`` gauges on the same registry ``get_metrics()`` and the
  proxy ``GET /stats`` already export.

Surfaces: proxy ``GET /healthz`` (readiness: 200/503 + JSON verdict),
the ``health`` REPL command in tools/dhtnode.py, the ``health`` section
of ``dhtscanner --json``, and the cluster aggregator
(testing/health_monitor.py + tools/dhtmon.py) that scrapes every node
and checks the cluster invariants (global lookup success, batched
replica coverage).

Reference mapping: the reference's only health surface is
``Dht::getNodesStats`` (src/dht.cpp:1424-1444) — raw routing counters a
human inspects.  This module is what a service fleet needs instead: the
counters stay (folded into ``dht_routing_*`` since round 8), and the
verdict machine on top is the part the reference leaves to the reader.

Import-light by design (stdlib + the telemetry/tracing spine) so the
evaluator runs in minimal containers and pure-registry unit tests.
"""

from __future__ import annotations

import logging
import time as _time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from . import telemetry, tracing
from .telemetry import _bucket_index, _bucket_le

log = logging.getLogger("opendht_tpu.health")

__all__ = [
    "HEALTHY", "DEGRADED", "UNHEALTHY", "SloObjective", "HealthConfig",
    "HealthEvaluator", "NodeHealth", "default_slos", "parse_alerts",
    "percentile_breaches", "quantile_from_cumulative",
]

HEALTHY, DEGRADED, UNHEALTHY = "healthy", "degraded", "unhealthy"
_RANK = {HEALTHY: 0, DEGRADED: 1, UNHEALTHY: 2}
_BY_RANK = (HEALTHY, DEGRADED, UNHEALTHY)


# ===================================================== shared alert grammar
def parse_alerts(specs) -> dict:
    """``["p95=2.5", "50=1"]`` → {95: 2.5, 50: 1.0}; raises ValueError
    on malformed specs or percentiles outside (0, 100).  The ONE
    ``--alert PCT=SEC`` grammar shared by testing/network_monitor.py,
    testing/health_monitor.py and tools/dhtmon.py (ISSUE-9 satellite:
    this helper moved here from network_monitor)."""
    out: dict = {}
    for spec in specs or ():
        name, _, thr = spec.partition("=")
        if not thr:
            raise ValueError("alert spec %r is not PCT=SECONDS" % spec)
        p = float(name.lstrip("pP"))
        if not 0 < p < 100:
            raise ValueError("alert percentile %r outside (0, 100)" % name)
        out[p] = float(thr)
    return out


def percentile_breaches(quantile_fn: Callable[[float], Optional[float]],
                        alerts: dict) -> List[Tuple[float, float, float]]:
    """Evaluate one ``parse_alerts`` threshold map against a quantile
    source (``quantile_fn(q)`` with q in (0,1); None = no data).
    Returns ``[(pct, observed, threshold)]`` for every breached alert —
    the cumulative-percentile check network_monitor and dhtmon share."""
    out = []
    for pct, thr in sorted(alerts.items()):
        v = quantile_fn(pct / 100.0)
        if v is not None and v > thr:
            out.append((pct, v, thr))
    return out


def quantile_from_cumulative(pairs: List[Tuple[float, float]],
                             q: float) -> Optional[float]:
    """Linear-interpolated quantile over cumulative ``(le, count)``
    pairs (a Prometheus ``_bucket`` series, or any cumulative
    histogram) — the exposition-side twin of
    :meth:`telemetry.Histogram.quantile`.  ``None`` when empty."""
    pairs = sorted((float(le), float(c)) for le, c in pairs
                   if le != float("inf"))
    total = pairs[-1][1] if pairs else 0.0
    if total <= 0:
        return None
    target = q * total
    prev_le, prev_c = 0.0, 0.0
    for le, c in pairs:
        if c >= target:
            inb = c - prev_c
            frac = (target - prev_c) / inb if inb > 0 else 1.0
            return prev_le + (le - prev_le) * min(max(frac, 0.0), 1.0)
        prev_le, prev_c = le, c
    return pairs[-1][0]


# ========================================================== configuration
@dataclass(frozen=True)
class SloObjective:
    """One declarative objective over the ``dht_op_seconds`` /
    ``dht_ops_total`` series of a public op.

    - ``kind="availability"``: ``objective`` is the target success
      fraction of ``dht_ops_total{op=,ok=}`` (bad = ``ok="false"``).
    - ``kind="latency"``: ``objective`` is the target fraction of
      ``dht_op_seconds{op=}`` observations at or under ``threshold_s``
      (bad = over-threshold ops) — the standard reduction that lets one
      burn-rate machine serve both objective kinds."""

    name: str
    op: str
    kind: str = "availability"
    objective: float = 0.99
    threshold_s: float = 1.0


def default_slos() -> tuple:
    """The default per-op objective set: 99% availability on the three
    public op families, 95% of gets/puts under 4 s (generous enough
    for WAN deployments; tighten via ``Config.health.slos``).  4 s is
    a log-bucket EDGE, so the default over-threshold counts are exact,
    not interpolated (see :func:`_count_over`)."""
    return (
        SloObjective("get_availability", "get"),
        SloObjective("put_availability", "put"),
        SloObjective("listen_availability", "listen"),
        SloObjective("get_latency", "get", "latency", 0.95, 4.0),
        SloObjective("put_latency", "put", "latency", 0.95, 4.0),
    )


#: per-signal (degraded, unhealthy) thresholds; values are fractions
#: except scheduler_lag (seconds, windowed p95) and connectivity
#: (0 = connected, 1 = connecting, 2 = disconnected)
DEFAULT_SIGNAL_THRESHOLDS = {
    "connectivity": (0.5, 1.5),
    "ingest_queue": (0.5, 0.9),
    "scheduler_lag": (0.5, 2.0),
    "timeout_ratio": (0.5, 0.9),
    "stale_buckets": (0.6, 0.95),
    # round 15 (ISSUE-10): max/mean per-shard keyspace traffic off the
    # observatory's folded histogram — 1.0 is perfect balance, t is a
    # single-shard flood.  3x the fair share degrades; 6x (a de-facto
    # single-key/single-shard hot spot at the default 8-way
    # attribution) would be unhealthy, but the signal is capped at
    # degraded in the verdict by default (HealthConfig.degrade_only) —
    # see the field comment.  Unknown below the observatory's
    # min_observed window, so boot noise never trips it.
    "shard_imbalance": (3.0, 6.0),
    # round 16 (ISSUE-11): the hot-key serving cache's windowed MISS
    # fraction (1 − dht_cache_hit_ratio) — the engine thresholds on
    # "bigger is worse", so the signal VALUE is the miss side of the
    # ratio the gauges/dhtmon report.  Unknown (never trips) while the
    # cache is disabled, dark, or had no eligible probes in the
    # window; capped at degraded in the verdict (degrade_only): a cold
    # cache is an efficiency problem, not a liveness one.
    "cache_hit_ratio": (0.5, 0.9),
    # round 19 (ISSUE-15): the waterfall's windowed worst-stage
    # p95/budget ratio (waterfall.StageProfiler.stage_budget) — 1.0
    # means the slowest serving stage sits exactly at its budgeted
    # p95; 2.0 is a 2x blowout.  Unknown until a stage accrues enough
    # samples in the window, device_compile excluded (one-time XLA
    # lowering).  Capped at degraded in the verdict (degrade_only): a
    # slow stage is an efficiency regression, not lost liveness.
    "stage_budget": (1.0, 2.0),
    # round 22 (ISSUE-18): occupancy collapse — the pipeline
    # observatory's windowed fraction of wall clock lost to STARVED
    # device-idle bubbles (fill_slow / drain_backpressure /
    # launch_retry / reshard_swap; queue_empty and cache_served are
    # healthy idleness and never count).  Half the window starved
    # degrades; 0.9 would be unhealthy-grade, but the signal is capped
    # at degraded in the verdict (degrade_only): a starved pipeline is
    # an efficiency collapse, not lost liveness.  Unknown (never
    # trips) while the observatory is off or the window saw no waves.
    "pipeline_occupancy": (0.5, 0.9),
    # round 23 (ISSUE-19): worst single-link fail ratio from the
    # per-peer ledger (opendht_tpu/peers.py) — expired / finished
    # requests of the worst peer with at least
    # Config.peers.min_signal_events requests.  Half the requests to
    # ONE peer failing degrades; 0.9 would be unhealthy-grade, but the
    # signal is capped at degraded in the verdict (degrade_only): one
    # bad link (or one dead remote peer flapping good<->dubious<->
    # expired) is a wire problem to route around, not lost liveness of
    # THIS node — the cluster-wide view is already timeout_ratio.
    # Unknown (never trips) while the ledger is off or no peer
    # qualifies.
    "peer_flap": (0.5, 0.9),
}


@dataclass
class HealthConfig:
    """Declarative health/SLO configuration (lives on
    ``runtime.config.Config.health``)."""

    #: seconds between evaluator ticks on the node scheduler; 0 = the
    #: runner never attaches an evaluator (health surfaces report
    #: verdict "unknown")
    period: float = 1.0
    slos: tuple = field(default_factory=default_slos)
    #: fast-burn pair: sudden total failure trips within one window
    fast_window: float = 60.0
    fast_burn: float = 14.4
    #: slow-burn pair: a sustained modest budget leak
    slow_window: float = 600.0
    slow_burn: float = 6.0
    #: hysteresis: a tripped window clears only below
    #: ``threshold * recover_ratio`` (no flapping on a boundary value)
    recover_ratio: float = 0.8
    #: a window with fewer events than this never trips (one failed op
    #: at boot is not an outage)
    min_events: int = 4
    #: signal name -> (degraded, unhealthy) threshold pair
    signal_thresholds: dict = field(
        default_factory=lambda: dict(DEFAULT_SIGNAL_THRESHOLDS))
    #: signals whose level is capped at degraded in the verdict:
    #: load-balance attribution is capacity planning, not liveness —
    #: legitimately concentrated traffic (a republish calendar bin's
    #: searches all land XOR-close to the node's own id, one narrow
    #: ring slice) can exceed the unhealthy threshold for a window on
    #: a perfectly healthy node, and must not 503 its /healthz
    #: readiness behind a load balancer (review finding).
    #: cache_hit_ratio rides the same cap (round 16): a cold or
    #: miss-heavy cache degrades efficiency, never liveness.
    #: stage_budget joins it (round 19): a stage past its latency
    #: budget is slow serving, not a down node.  pipeline_occupancy
    #: joins it (round 22): a starved pipeline serves slowly, it is
    #: not dead.  peer_flap joins it (round 23): ONE bad link is a
    #: wire problem to route around, not lost liveness of this node.
    degrade_only: tuple = ("shard_imbalance", "cache_hit_ratio",
                           "stage_budget", "pipeline_occupancy",
                           "peer_flap")


# ====================================================== window bookkeeping
class _Window:
    """History of cumulative sample tuples -> windowed deltas.  Keeps
    one entry older than ``keep`` as the baseline for the longest
    window; all math is snapshot subtraction, so the underlying series
    stay untouched."""

    __slots__ = ("keep", "_h")

    def __init__(self, keep: float):
        self.keep = keep
        self._h: deque = deque()

    def push(self, t: float, vals) -> None:
        self._h.append((t, vals))
        cutoff = t - self.keep
        while len(self._h) > 2 and self._h[1][0] <= cutoff:
            self._h.popleft()

    def delta(self, now: float, window: float):
        """``(baseline_vals, current_vals, span_s)`` against the newest
        entry at least ``window`` old (or the oldest held — a young
        process evaluates over its whole life); None before two
        snapshots exist."""
        if len(self._h) < 2:
            return None
        target = now - window
        base = self._h[0]
        for ent in self._h:
            if ent[0] <= target:
                base = ent
            else:
                break
        cur = self._h[-1]
        if cur[0] <= base[0]:
            return None
        return base[1], cur[1], cur[0] - base[0]


def _count_over(dbuckets: Dict[int, int], threshold: float) -> float:
    """Observations above ``threshold`` in a bucket-index delta map
    (log-bucket scheme of telemetry.Histogram), interpolating inside
    the landing bucket.  Exact when the threshold is a power of two
    (the bucket edge), which the SLO defaults and tests use."""
    i = _bucket_index(threshold)
    over = 0.0
    for j, c in dbuckets.items():
        if c <= 0:
            continue
        if j > i:
            over += c
        elif j == i:
            lo = 0.0 if i == 0 else _bucket_le(i - 1)
            hi = _bucket_le(i)
            frac = (hi - threshold) / (hi - lo) if hi > lo else 0.0
            over += c * min(max(frac, 0.0), 1.0)
    return over


def _delta_quantile(dbuckets: Dict[int, int], q: float) -> Optional[float]:
    """Quantile over a bucket-index delta map — the SAME interpolator
    as telemetry.Histogram.quantile (one shared copy,
    telemetry.quantile_from_buckets); None when the window saw
    nothing."""
    items = sorted((i, c) for i, c in dbuckets.items() if c > 0)
    total = sum(c for _i, c in items)
    if total <= 0:
        return None
    return telemetry.quantile_from_buckets(items, total, q)


def _sub_buckets(cur: Dict[int, int], base: Dict[int, int]) -> Dict[int, int]:
    return {i: cur.get(i, 0) - base.get(i, 0)
            for i in set(cur) | set(base)}


# ============================================================ SLO engine
class _SloState:
    """Per-objective burn-rate state: cumulative snapshots + the two
    window trip latches (with hysteresis)."""

    __slots__ = ("obj", "win", "fast_active", "slow_active", "level",
                 "detail")

    def __init__(self, obj: SloObjective, keep: float):
        self.obj = obj
        self.win = _Window(keep)
        self.fast_active = False
        self.slow_active = False
        self.level = HEALTHY
        self.detail: dict = {}


def _latch(active: bool, trip_burn: Optional[float],
           clear_burn: Optional[float], threshold: float,
           recover: float) -> bool:
    """Trip/clear one window latch with asymmetric evidence rules:

    - TRIPPING uses ``trip_burn`` (None below ``min_events`` — one
      failed op at boot is not an outage).
    - CLEARING uses ``clear_burn``, which is computable whenever the
      window itself is (zero events in the window = burn 0: once the
      window has rolled completely past the failure, holding the latch
      would deadlock a drained node — /healthz 503 → LB sends no
      traffic → no events → 503 forever; review finding).  ``None``
      (window not yet computable) keeps the previous state."""
    if active:
        if clear_burn is None:
            return True
        return clear_burn >= threshold * recover
    if trip_burn is None:
        return False
    return trip_burn >= threshold


def sustain_latch(since: Optional[float], now: float,
                  value: Optional[float], threshold: float,
                  recover: float) -> Optional[float]:
    """Timestamped form of :func:`_latch` for raw gauges — the shared
    sustain-window hysteresis rule (used by the reshard tick,
    opendht_tpu/reshard.py, against ``dht_shard_imbalance``).

    ``since`` is the time the value first exceeded ``threshold`` (None
    = not latched).  Tripping needs ``value > threshold``; once
    latched, clearing needs the value to fall below
    ``threshold·recover`` — inside the hysteresis band the latch (and
    its start time) holds, so a value oscillating around the threshold
    accumulates ONE sustain window instead of restarting the clock at
    every dip.  An unknown value keeps the previous state (same rule
    as the SLO latch: no evidence is not recovery)."""
    if value is None:
        return since
    if since is not None:
        return None if value < threshold * recover else since
    return now if value > threshold else None


class HealthEvaluator:
    """The registry-reading verdict machine (see module docstring).

    Pure host-side: every tick snapshots cumulative series, computes
    windowed burn rates and signal levels, rolls the verdict, exports
    ``dht_health_*`` / ``dht_slo_*`` gauges and emits the two flight
    events on transitions.  ``providers`` maps extra signal names to
    zero-arg callables returning the signal value (None = unknown);
    the two registry-derived signals (scheduler tick lag, request
    timeout ratio) are built in."""

    def __init__(self, cfg: Optional[HealthConfig] = None, *,
                 registry: Optional[telemetry.MetricsRegistry] = None,
                 tracer: Optional[tracing.Tracer] = None,
                 clock: Callable[[], float] = _time.monotonic,
                 node: str = "",
                 providers: Optional[Dict[str, Callable]] = None,
                 history=None):
        self.cfg = cfg or HealthConfig()
        self.reg = registry or telemetry.get_registry()
        self.tracer = tracer or tracing.get_tracer()
        self.clock = clock
        self.node = node
        #: round-17 flight data recorder (opendht_tpu/history.py).
        #: When attached, EVERY windowed delta — SLO windows, the
        #: scheduler-lag p95, the timeout ratio — reads through its
        #: retained frames instead of this evaluator's private
        #: ``_Window`` prior-snapshot state: ONE delta codepath (the
        #: round-15 ``quantile_from_buckets`` consolidation move,
        #: applied to the windowing layer), and the evidence the
        #: verdict was derived from survives in the ring for the
        #: post-mortem bundle.  The recorder must share this
        #: evaluator's clock (runtime/runner.py passes the scheduler
        #: clock to both).
        self.history = history
        #: optional hook fired AFTER a verdict transition is recorded:
        #: ``on_transition(prev, new, report)`` — runtime/runner.py
        #: captures the black-box bundle here (round 17).  Exceptions
        #: are swallowed: a broken bundle hook must not kill the tick.
        self.on_transition: Optional[Callable] = None
        # node-keyed export labels: co-resident nodes share the process
        # registry (round-8 semantics), so an unlabeled verdict gauge
        # would be last-writer-wins across nodes; standalone evaluators
        # (node="") stay unlabeled
        self._labels = {"node": node} if node else {}
        self.providers = dict(providers or {})
        keep = self.cfg.slow_window * 1.25
        self._slos = [_SloState(o, keep) for o in self.cfg.slos]
        self._lag_win = _Window(keep)
        self._timeout_win = _Window(keep)
        self._signal_levels: Dict[str, str] = {}
        self._verdict = "unknown"
        self._since = self.clock()
        self._report: dict = {"verdict": "unknown", "since": self._since,
                              "signals": {}, "slo": {}, "unknown": []}

    # ----------------------------------------------------------- sampling
    def _slo_sample(self, st: _SloState) -> tuple:
        """Current cumulative (total, bad[, buckets]) of one objective.
        Read through the non-mutating :meth:`~telemetry.MetricsRegistry
        .series` accessor — the get-or-create factories would register
        permanently-zero series for ops that never ran, polluting every
        later ``/stats`` scrape (review finding)."""
        o = st.obj
        if o.kind == "availability":
            ok = bad = 0.0
            for key, m in self.reg.series("dht_ops_total").items():
                labels = dict(key)
                if labels.get("op") != o.op:
                    continue
                if labels.get("ok") == "false":
                    bad += m.value
                else:
                    ok += m.value
            return (ok + bad, bad)
        for key, m in self.reg.series("dht_op_seconds").items():
            if dict(key).get("op") == o.op:
                count, _total, buckets = m.raw()
                return (count, buckets)
        return (0, {})

    def _slo_window(self, st: _SloState, now: float,
                    window: float) -> Optional[tuple]:
        """Windowed ``(total, bad)`` of one objective; None before two
        snapshots exist (the window itself is not computable yet)."""
        d = st.win.delta(now, window)
        if d is None:
            return None
        base, cur, _span = d
        if st.obj.kind == "availability":
            return max(cur[0] - base[0], 0.0), max(cur[1] - base[1], 0.0)
        dtotal = max(cur[0] - base[0], 0.0)
        dbuckets = _sub_buckets(cur[1], base[1])
        return dtotal, _count_over(dbuckets, st.obj.threshold_s)

    def _slo_window_hist(self, st: _SloState, now: float,
                         window: float) -> Optional[tuple]:
        """Windowed ``(total, bad)`` read through the attached history
        recorder's frames (round 17) — same None-before-coverage
        contract as :meth:`_slo_window`.  Series names are the exact
        Prometheus forms the recorder keys frames by (labels sorted,
        telemetry._series_name)."""
        o = st.obj
        t0 = now - window
        if o.kind == "availability":
            ok = self.history.counter_delta(
                'dht_ops_total{ok="true",op="%s"}' % o.op, t0, now)
            if ok is None:        # no frame covers the window yet
                return None
            bad = self.history.counter_delta(
                'dht_ops_total{ok="false",op="%s"}' % o.op, t0, now) or 0.0
            return ok + bad, bad
        d = self.history.hist_delta('dht_op_seconds{op="%s"}' % o.op,
                                    t0, now)
        if d is None:
            return None
        count, _sum, buckets = d
        return count, _count_over(buckets, o.threshold_s)

    def _eval_slo(self, st: _SloState, now: float) -> None:
        cfg = self.cfg
        if self.history is None:
            st.win.push(now, self._slo_sample(st))
        budget = max(1.0 - st.obj.objective, 1e-9)
        burns = {}
        clears = {}
        any_data = False
        for wname, wlen in (("fast", cfg.fast_window),
                            ("slow", cfg.slow_window)):
            w = (self._slo_window_hist(st, now, wlen)
                 if self.history is not None
                 else self._slo_window(st, now, wlen))
            total, bad = w if w is not None else (0.0, 0.0)
            if w is not None and total >= cfg.min_events:
                any_data = True
                burns[wname] = {"events": total, "bad": bad,
                                "rate": bad / total,
                                "burn": (bad / total) / budget}
            else:
                burns[wname] = {"events": total, "bad": bad,
                                "rate": None, "burn": None}
            # clearing evidence: computable whenever the window is —
            # an empty window means the failure rolled out (burn 0)
            clears[wname] = (None if w is None else
                             ((bad / total) / budget if total else 0.0))
        st.fast_active = _latch(st.fast_active, burns["fast"]["burn"],
                                clears["fast"], cfg.fast_burn,
                                cfg.recover_ratio)
        st.slow_active = _latch(st.slow_active, burns["slow"]["burn"],
                                clears["slow"], cfg.slow_burn,
                                cfg.recover_ratio)
        prev = st.level
        st.level = (UNHEALTHY if st.fast_active
                    else DEGRADED if st.slow_active else HEALTHY)
        st.detail = {
            "kind": st.obj.kind, "op": st.obj.op,
            "objective": st.obj.objective,
            "threshold_s": (st.obj.threshold_s
                            if st.obj.kind == "latency" else None),
            "level": st.level, "unknown": not any_data,
            "fast": burns["fast"], "slow": burns["slow"],
        }
        for wname in ("fast", "slow"):
            b = burns[wname]["burn"]
            self.reg.gauge("dht_slo_burn_rate", objective=st.obj.name,
                           window=wname, **self._labels).set(
                -1.0 if b is None else b)
        self.reg.gauge("dht_slo_violation", objective=st.obj.name,
                       **self._labels).set(_RANK[st.level])
        if _RANK[st.level] > _RANK.get(prev, 0) and self.tracer.enabled:
            self.tracer.event(
                "slo_violation", node=self.node, objective=st.obj.name,
                level=st.level, op=st.obj.op,
                fast_burn=burns["fast"]["burn"],
                slow_burn=burns["slow"]["burn"])

    # ------------------------------------------------------------ signals
    def _builtin_signals(self, now: float) -> Dict[str, Optional[float]]:
        cfg = self.cfg
        out: Dict[str, Optional[float]] = {}
        if self.history is not None:
            # round 17: the same two windowed signals, read through the
            # recorder's frames (family-prefix matching folds the
            # type-labeled request series exactly like the series()
            # sums below) — no private window state
            t0 = now - cfg.fast_window
            out["scheduler_lag"] = self.history.quantile(
                "dht_scheduler_tick_lag_seconds", 0.95, t0, now)
            dsent = self.history.counter_delta(
                "dht_net_requests_sent_total", t0, now)
            dexp = self.history.counter_delta(
                "dht_net_requests_expired_total", t0, now)
            ratio = None
            if dsent is not None and dsent >= cfg.min_events:
                ratio = max(dexp or 0.0, 0.0) / dsent
            out["timeout_ratio"] = ratio
            return out
        # scheduler tick lag: windowed p95 of the round-8 histogram
        count, _s, buckets = self.reg.histogram(
            "dht_scheduler_tick_lag_seconds").raw()
        self._lag_win.push(now, (count, buckets))
        d = self._lag_win.delta(now, cfg.fast_window)
        lag = None
        if d is not None:
            lag = _delta_quantile(_sub_buckets(d[1][1], d[0][1]), 0.95)
        out["scheduler_lag"] = lag
        # request timeout ratio: expired / sent deltas over every type
        sent = sum(m.value for m in
                   self.reg.series("dht_net_requests_sent_total").values())
        expired = sum(m.value for m in self.reg.series(
            "dht_net_requests_expired_total").values())
        self._timeout_win.push(now, (sent, expired))
        d = self._timeout_win.delta(now, cfg.fast_window)
        ratio = None
        if d is not None:
            dsent = d[1][0] - d[0][0]
            if dsent >= cfg.min_events:
                ratio = max(d[1][1] - d[0][1], 0.0) / dsent
        out["timeout_ratio"] = ratio
        return out

    def _eval_signals(self, now: float) -> Dict[str, dict]:
        cfg = self.cfg
        values = self._builtin_signals(now)
        for name, fn in self.providers.items():
            try:
                values[name] = fn()
            except Exception:
                log.exception("health signal provider %r failed", name)
                values[name] = None
        out: Dict[str, dict] = {}
        for name, value in values.items():
            deg, unh = cfg.signal_thresholds.get(name, (0.5, 0.9))
            prev = self._signal_levels.get(name, HEALTHY)
            if value is None:
                level = prev       # no data neither trips nor clears
                unknown = True
            else:
                unknown = False
                # hysteresis on the same recover_ratio as the SLOs
                d_thr = deg * (cfg.recover_ratio
                               if _RANK.get(prev, 0) >= 1 else 1.0)
                u_thr = unh * (cfg.recover_ratio
                               if _RANK.get(prev, 0) >= 2 else 1.0)
                level = (UNHEALTHY if value >= u_thr
                         else DEGRADED if value >= d_thr else HEALTHY)
                if level == UNHEALTHY and name in cfg.degrade_only:
                    level = DEGRADED
            self._signal_levels[name] = level
            out[name] = {"level": level, "value": value,
                         "unknown": unknown,
                         "degraded": deg, "unhealthy": unh}
            # the gauge reports the RETAINED level while the source is
            # unknown (an alert on >= degraded must not clear mid-
            # incident just because the signal went dark — review
            # finding); -1 only when unknown AND healthy
            self.reg.gauge("dht_health_signal", signal=name,
                           **self._labels).set(
                -1.0 if value is None and level == HEALTHY
                else _RANK[level])
        return out

    # --------------------------------------------------------------- tick
    def tick(self, now: Optional[float] = None) -> dict:
        """One evaluation pass; returns (and retains) the report dict."""
        now = self.clock() if now is None else now
        for st in self._slos:
            self._eval_slo(st, now)
        signals = self._eval_signals(now)
        worst = HEALTHY
        causes: List[str] = []
        for name, sig in signals.items():
            if _RANK[sig["level"]] > _RANK[worst]:
                worst, causes = sig["level"], [name]
            elif sig["level"] == worst and _RANK[worst] > 0:
                causes.append(name)
        for st in self._slos:
            if _RANK[st.level] > _RANK[worst]:
                worst, causes = st.level, [st.obj.name]
            elif st.level == worst and _RANK[worst] > 0:
                causes.append(st.obj.name)
        unknown = sorted(
            [n for n, s in signals.items() if s["unknown"]]
            + [st.obj.name for st in self._slos
               if st.detail.get("unknown")])
        prev_verdict = self._verdict
        if worst != self._verdict:
            self._verdict = worst
            self._since = now
            if self.tracer.enabled:
                self.tracer.event("health_transition", node=self.node,
                                  **{"from": prev_verdict, "to": worst,
                                     "causes": sorted(set(causes))})
        self.reg.gauge("dht_health_status", **self._labels).set(
            _RANK[worst])
        report = {
            "verdict": worst,
            "since": self._since,
            "time": now,
            "causes": sorted(set(causes)),
            "signals": signals,
            "slo": {st.obj.name: st.detail for st in self._slos},
            "unknown": unknown,
        }
        self._report = report
        if worst != prev_verdict and self.on_transition is not None:
            try:
                self.on_transition(prev_verdict, worst, report)
            except Exception:
                log.exception("health on_transition hook failed")
        return report

    def report(self) -> dict:
        """The last tick's report (atomic reference swap: safe to read
        from proxy handler threads while the DHT thread ticks)."""
        return self._report

    @property
    def verdict(self) -> str:
        return self._verdict


# ============================================================ node glue
_STATUS_VALUE = {"CONNECTED": 0.0, "CONNECTING": 1.0, "DISCONNECTED": 2.0}


class NodeHealth:
    """Per-node glue: derives the node-level signals from a live
    :class:`~opendht_tpu.runtime.dht.Dht` and runs the evaluator on a
    periodic scheduler tick (``runtime/runner.py`` constructs and
    attaches one per node when ``Config.health.period > 0``)."""

    def __init__(self, dht, cfg: Optional[HealthConfig] = None,
                 node: str = "", history=None):
        self._dht = dht
        self._node_id = str(getattr(dht, "myid", "") or "")
        self.cfg = cfg or HealthConfig()
        self.evaluator = HealthEvaluator(
            self.cfg, clock=dht.scheduler.time, node=node,
            history=history,
            providers={
                "connectivity": self._connectivity,
                "ingest_queue": self._ingest_queue,
                "stale_buckets": self._stale_buckets,
                "shard_imbalance": self._shard_imbalance,
                "cache_hit_ratio": self._cache_hit_ratio,
                "stage_budget": self._stage_budget,
                "pipeline_occupancy": self._pipeline_occupancy,
                "peer_flap": self._peer_flap,
            })
        self._job = None

    # ------------------------------------------------------------ signals
    def _connectivity(self) -> float:
        return _STATUS_VALUE.get(self._dht.get_status().name, 2.0)

    def _ingest_queue(self) -> float:
        wb = self._dht.wave_builder
        if not wb.enabled:
            return 0.0           # no admission queue to saturate
        if wb.queue_max <= 0:
            # a zero bound sheds EVERY new op (WaveBuilder.admit:
            # len(pending) >= 0) — the most-saturated state, not the
            # least (review finding: this read 0.0 = healthiest)
            return 1.0
        return wb.pending() / wb.queue_max

    #: a family's stale fraction only counts when its table has at
    #: least this many occupied buckets — below it (small / freshly
    #: bootstrapped clusters) one never-replied peer swings the
    #: fraction 0→1 and a "stale" verdict would be pure noise (a
    #: 100k-node swarm sits at ~17+ occupied buckets)
    STALE_MIN_OCCUPIED = 8

    def _stale_buckets(self) -> Optional[float]:
        """Max per-family stale-bucket fraction of THIS node, read off
        the node-keyed gauges the round-10 maintenance sweep publishes
        (no extra device launch on the health tick — the sweep already
        ran; the node label keeps co-resident nodes from reading each
        other's sweeps).  Families whose occupancy is below
        :data:`STALE_MIN_OCCUPIED` are skipped; with no qualifying
        family the signal is unknown."""
        reg = telemetry.get_registry()
        fractions = reg.series("dht_maintenance_stale_fraction")
        occupied = reg.series("dht_maintenance_occupied_buckets")
        vals = [m.value for key, m in fractions.items()
                if dict(key).get("node") == self._node_id
                and occupied.get(key) is not None
                and occupied[key].value >= self.STALE_MIN_OCCUPIED]
        return max(vals) if vals else None

    def _shard_imbalance(self) -> Optional[float]:
        """Max/mean per-shard keyspace traffic from the round-15
        observatory (opendht_tpu/keyspace.py) — already folded over
        the live t-sharded row boundaries (or the uniform virtual
        split) on the observatory's own tick, so this is one attribute
        read.  None (unknown) while the window holds fewer than
        ``min_observed`` ids — a quiet node is not imbalanced."""
        ks = getattr(self._dht, "keyspace", None)
        return ks.imbalance() if ks is not None else None

    def _cache_hit_ratio(self) -> Optional[float]:
        """Windowed MISS fraction of the round-16 hot-key serving
        cache (``1 − hotcache.hit_ratio()``) — the engine's thresholds
        compare "bigger is worse", so the signal value is the miss
        side of the ratio the ``dht_cache_hit_ratio`` gauge and
        ``dhtmon --min-cache-hit`` report.  None (unknown, never
        trips) while the cache is disabled/dark or saw no eligible
        probes in the last observatory window — a quiet cache is not a
        cold one.  Degrade-only in the verdict
        (:class:`HealthConfig`.degrade_only)."""
        hc = getattr(self._dht, "hotcache", None)
        if hc is None:
            return None
        ratio = hc.hit_ratio()
        return None if ratio is None else 1.0 - ratio

    def _stage_budget(self) -> Optional[float]:
        """Worst-stage p95/budget ratio from the round-19 latency
        waterfall over the window since the last health tick (the
        profiler diffs its stage histograms against the previous call's
        baselines, so the tick cadence IS the window).  None (unknown,
        never trips) while no stage accrued enough new samples — a
        quiet node has no slow stages.  Degrade-only in the verdict
        (:class:`HealthConfig`.degrade_only)."""
        from . import waterfall
        return waterfall.get_profiler().stage_budget()

    def _pipeline_occupancy(self) -> Optional[float]:
        """Occupancy collapse from the round-22 pipeline observatory:
        windowed fraction of wall clock lost to STARVED device-idle
        bubbles (the tick cadence IS the window, stage_budget-style).
        Healthy idleness — queue_empty, cache_served — never counts,
        so an idle node stays healthy and a flooded-but-starved one
        degrades.  None (unknown, never trips) while the observatory
        is off or the window saw no pipeline activity.  Degrade-only
        in the verdict (:class:`HealthConfig`.degrade_only), with the
        engine's standard hysteresis on recovery."""
        wb = getattr(self._dht, "wave_builder", None)
        obs = getattr(wb, "observatory", None)
        if obs is None or not obs.enabled:
            return None
        return obs.collapse()

    def _peer_flap(self) -> Optional[float]:
        """Worst single-link fail ratio from the round-23 per-peer
        ledger (opendht_tpu/peers.py): expired / finished requests of
        the worst peer with at least ``Config.peers.min_signal_events``
        requests — the per-link view next to the cluster-wide
        ``timeout_ratio``.  None (unknown, never trips) while the
        ledger is off or no peer has enough traffic to judge.
        Degrade-only in the verdict
        (:class:`HealthConfig`.degrade_only)."""
        led = getattr(self._dht, "peers", None)
        if led is None or not getattr(led, "enabled", False):
            return None
        return led.fail_signal()

    # --------------------------------------------------------------- tick
    def attach(self, scheduler) -> None:
        """Schedule the periodic evaluation on the node scheduler."""
        if self.cfg.period <= 0 or self._job is not None:
            return
        # _sched must exist before the job can possibly fire: attach on
        # a LIVE node races _tick_job's reschedule otherwise
        self._sched = scheduler
        self._job = scheduler.add(scheduler.time() + self.cfg.period,
                                  self._tick_job)

    def _tick_job(self) -> None:
        try:
            self.tick()
        finally:
            self._job = self._sched.add(
                self._sched.time() + self.cfg.period, self._tick_job)

    def tick(self) -> dict:
        return self.evaluator.tick()

    def report(self) -> dict:
        return self.evaluator.report()
