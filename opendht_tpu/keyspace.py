"""Keyspace traffic observatory: where in the 160-bit ring traffic lands.

Four observability layers (round-8 telemetry, round-9 tracing, round-11
kernel ledger, round-14 health) say how fast and how healthy the node
is; nothing said WHERE traffic lands — yet the whole architecture (the
row-sharded sorted table, the continuous-batching ingest waves) lives
or dies on keyspace load balance, and Kademlia's original design calls
for detecting popular keys to relieve hot spots via path caching
(Maymounkov & Mazières 2002 §4.1).  This module is that layer
(ISSUE-10 tentpole), built on the device count-min sketch of
:mod:`opendht_tpu.ops.sketch`:

- :class:`KeyspaceObservatory` owns the device ``[depth, width]``
  sketch + 256-bin top-8-bit histogram, updated by ONE batched
  scatter-add launch per ingest wave (``runtime/wave_builder.py``
  feeds the wave's ``[Q]`` target ids at ``_launch``; stored-key puts
  ride the same launch through :meth:`note_stored`'s pending buffer).
  Dispatch is async — the hot path never blocks on the sketch.
- **Heavy hitters**: a bounded host-side CANDIDATE set (sample-and-
  hold admission — every ``sample_stride``-th observed id, so a hot
  key is admitted with near-certainty while the host cost stays
  O(Q/stride) dict ops per wave) is re-scored against the sketch on a
  periodic scheduler tick (one batched ``sketch_query`` launch), and
  the top-K with estimates/shares is retained.  A key newly crossing
  the hot rule (share of window traffic >= ``hot_share`` AND estimate
  >= ``hot_min_count``) emits a ``hot_key_emerged`` flight event on
  the round-9 ring.
- **Windowing**: the tick applies exponential decay
  (``ops.sketch.sketch_decay``) so every surface reports a recent-
  traffic window, not a lifetime sum.
- **Shard load balance**: the 256-bin histogram is folded over the
  t-sharded table's row boundaries (:func:`fold_bins`; boundary bin
  positions from the actual shard boundary ids when a resolve mesh is
  live, a uniform ``virtual_shards`` split of the ring otherwise) into
  per-shard loads and one ``imbalance = max/mean`` ratio — the signal
  the round-14 health engine consumes (``shard_imbalance``) and
  ``dhtmon --max-imbalance`` gates on.

Surfaces: ``dht_keyspace_*`` / ``dht_hotkey_*`` / ``dht_shard_imbalance``
gauges on the unified registry (``get_metrics()`` + proxy ``GET
/stats``), the proxy ``GET /keyspace`` JSON snapshot, the ``keyspace``
REPL command in tools/dhtnode.py, and the ``keyspace`` section of
``dhtscanner --json``.

The sketch changes NO results anywhere: kernels are bit-identical with
the observatory on (pinned in tests/test_keyspace.py), accuracy is
pinned against an exact host-side ``Counter`` oracle (CMS overestimate
bound + top-K recall >= 0.9 on Zipf(1.1) traffic), the update launch
is cost-gated in perf_budgets.json (``sketch_update``), and the
measured on-cost on the 8192-wave round is committed in
captures/keyspace_overhead.json (<1% acceptance,
benchmarks/exp_keyspace_r15.py).

Import-light by design: this module imports only stdlib + the
telemetry/tracing spine at module scope; the device side (ops.sketch,
and through it jax) is looked up lazily on first observe, and a failed
jax backend degrades to a disabled observatory instead of failing the
node.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from . import telemetry, tracing
from .infohash import InfoHash

log = logging.getLogger("opendht_tpu.keyspace")

__all__ = [
    "KeyspaceConfig", "KeyspaceObservatory", "bin_edges_from_ids",
    "bin_edges_uniform", "fold_bins",
]

# local mirrors of ops.ids.HASH_BYTES / ops.ids.N_LIMBS / ops.sketch.BINS
# — ops.ids imports jax at module top, so importing the constants here
# would defeat this module's lazy-device design (the docstring's
# import-light contract); _ensure_device() cross-checks all three
# against the real modules the moment a device is available
HASH_BYTES = 20
N_LIMBS = 5
BINS = 256


# ========================================================== configuration
@dataclass
class KeyspaceConfig:
    """Declarative observatory configuration (lives on
    ``runtime.config.Config.keyspace``)."""

    #: master switch; "off" disables every launch and surface (the
    #: escape hatch — results identical either way, the sketch only
    #: observes)
    enabled: bool = True
    #: count-min geometry: depth rows x width columns of int32
    depth: int = 4
    width: int = 2048
    #: seconds between observatory ticks on the node scheduler (decay,
    #: heavy-hitter re-score, gauge refresh); 0 disables the tick
    tick: float = 2.0
    #: per-tick decay multiplier — counts are windowed, not lifetime
    #: (0.5 at a 2 s tick ~= a 4-6 s traffic window)
    decay: float = 0.5
    #: heavy hitters retained per tick
    top_k: int = 8
    #: host candidate-set bound (sample-and-hold admission)
    candidates: int = 512
    #: admit every Nth observed id into the candidate set (1 = every
    #: id; higher strides cut host cost, hot keys are still admitted
    #: with near-certainty because they recur)
    sample_stride: int = 8
    #: hot rule: a top-K key is HOT when its estimate is at least this
    #: share of the window total ...
    hot_share: float = 0.125
    #: ... and at least this absolute count (a 3-op boot window where
    #: one key is 2 of 3 observations is not a hot spot)
    hot_min_count: int = 32
    #: shard granularity for the imbalance signal when the table is
    #: NOT t-sharded (a uniform split of the ring — the load balance a
    #: t-way row-sharding WOULD see); a live resolve mesh overrides
    #: this with its actual shard boundaries
    virtual_shards: int = 8
    #: an imbalance below this many windowed observations is unknown,
    #: not a signal (absence of evidence is not imbalance)
    min_observed: int = 64
    #: bound on the stored-key pending buffer (drop-oldest): with
    #: ``tick=0`` and no wave traffic nothing ever drains it, and a
    #: put-only node would otherwise grow it for the process lifetime
    store_buffer: int = 4096


# ===================================================== histogram folding
def bin_edges_uniform(t: int, bins: int = BINS) -> List[float]:
    """Interior shard boundaries of a uniform t-way ring split, in
    fractional bin coordinates (len ``t - 1``)."""
    return [bins * s / t for s in range(1, t)]


def bin_edges_from_ids(boundary_ids, bins: int = BINS) -> List[float]:
    """Interior shard boundaries from the actual first-row ids of
    shards 1..t-1 of a sorted table (uint32 ``[t-1, 5]`` limbs or
    20-byte ids): fractional bin position = top-32-bits / 2^32 * bins.
    Bin-space resolution (2^-24 of a bin) is far below the 1-bin
    granularity the fold reports at."""
    arr = np.asarray(boundary_ids)
    if arr.dtype != np.uint32:
        from .ops.ids import ids_from_bytes
        arr = ids_from_bytes(arr.astype(np.uint8).reshape(-1, HASH_BYTES))
    top = arr.reshape(-1, N_LIMBS)[:, 0].astype(np.float64)
    return sorted((top / 2.0 ** 32 * bins).tolist())


def fold_bins(hist, edges: List[float]) -> List[float]:
    """Fold the per-bin counts over shard boundaries: shard ``s`` owns
    the keyspace ``[edges[s-1], edges[s])`` in bin coordinates, and a
    bin straddling an edge apportions its count by keyspace overlap
    (traffic is assumed uniform WITHIN a bin — the 1/256-ring
    resolution limit, stated in the snapshot).  Returns per-shard
    loads of length ``len(edges) + 1``; conserves ``sum(hist)``."""
    h = np.asarray(hist, np.float64)
    bounds = [0.0] + [min(max(float(e), 0.0), float(len(h)))
                      for e in edges] + [float(len(h))]
    loads = []
    for s in range(len(bounds) - 1):
        lo, hi = bounds[s], bounds[s + 1]
        if hi <= lo:
            loads.append(0.0)
            continue
        i0, i1 = int(np.floor(lo)), int(np.ceil(hi))
        total = 0.0
        for b in range(i0, min(i1, len(h))):
            c = h[b]
            if not c:
                continue
            overlap = min(hi, b + 1.0) - max(lo, float(b))
            if overlap > 0:
                total += float(c) * overlap
        loads.append(float(total))
    return loads


def _imbalance(loads: List[float]) -> Optional[float]:
    total = sum(loads)
    if total <= 0 or not loads:
        return None
    mean = total / len(loads)
    return float(max(loads) / mean)


# ============================================================ observatory
class KeyspaceObservatory:
    """Device sketch + histogram + host heavy-hitter state (module
    docstring).  One per :class:`~opendht_tpu.runtime.dht.Dht`
    (``dht.keyspace``); standalone construction (no scheduler) is the
    unit-test surface — call :meth:`tick` manually."""

    def __init__(self, cfg: Optional[KeyspaceConfig] = None, *,
                 node: str = "",
                 shard_info: Optional[Callable] = None):
        """``shard_info()`` (optional) returns ``(t, boundary_ids)``
        for the live t-sharded table — ``t <= 1`` or ``None`` ids fall
        back to the uniform ``virtual_shards`` split."""
        self.cfg = cfg or KeyspaceConfig()
        self.node = node
        self._labels = {"node": node} if node else {}
        self._shard_info = shard_info
        self._lock = threading.Lock()
        # device state (lazy: first observe imports ops.sketch/jax; a
        # failed backend downgrades to disabled instead of failing the
        # node)
        self._sketch = None
        self._hist = None
        self._device_ok: "bool | None" = None if self.cfg.enabled else False
        # host state
        self._pending_store: List[bytes] = []    # keys awaiting a launch
        self._candidates: Dict[bytes, int] = {}  # id bytes -> host hits
        self._sample_phase = 0
        self._observed_total = 0                 # lifetime (counter twin)
        self._window_total = 0.0                 # decayed window total
        # the window the published products were SCORED against (set
        # per tick, pre-decay): snapshot/gauges must report estimates,
        # shares and window_total from the same instant — publishing
        # the post-decay accumulator made estimate > window_total and
        # share inconsistent by 1/decay (review finding)
        self._window_published = 0.0
        self._since_tick = 0
        # tick products (read by snapshot()/health from other threads;
        # replaced wholesale under the lock)
        self._top: List[dict] = []
        self._hot: set = set()
        self._loads: List[float] = []
        self._shard_t = 0
        self._shard_virtual = True
        self._imbalance: Optional[float] = None
        self._hist_host = np.zeros((BINS,), np.int64)
        self._job = None
        self._m_obs: Dict[str, object] = {}      # source -> counter
        # tick subscribers (ISSUE-11): the hot-key serving cache (and
        # anything else acting on the observatory's products) receives
        # each tick's heavy-hitter list — the observe→act seam
        self._subscribers: List[Callable] = []

    # ------------------------------------------------------------- device
    def _ensure_device(self) -> bool:
        if self._device_ok is not None:
            return self._device_ok
        try:
            from .ops import ids as _ids
            from .ops import sketch as sk
            if (sk.BINS, _ids.HASH_BYTES, _ids.N_LIMBS) != (
                    BINS, HASH_BYTES, N_LIMBS):
                raise AssertionError(
                    "keyspace constant mirrors drifted from ops: "
                    f"{(sk.BINS, _ids.HASH_BYTES, _ids.N_LIMBS)} != "
                    f"{(BINS, HASH_BYTES, N_LIMBS)}")
            self._sketch, self._hist = sk.sketch_init(
                self.cfg.depth, self.cfg.width)
            self._device_ok = True
        except Exception:
            log.warning("keyspace sketch unavailable (no jax backend?); "
                        "observatory disabled", exc_info=True)
            self._device_ok = False
        return self._device_ok

    @property
    def enabled(self) -> bool:
        return self.cfg.enabled and self._device_ok is not False

    def _go_dark_locked(self) -> None:
        """Device failure: disable AND clear every published product
        (callers hold the lock).  A dead observatory must report
        unknown/empty, not the last window forever — the health signal
        reads :meth:`imbalance` every period, and a stale 7.0 would
        hold the node unhealthy on no evidence (review finding)."""
        self._device_ok = False
        self._imbalance = None
        self._top = []
        self._hot = set()
        self._loads = []
        self._hist_host = np.zeros((BINS,), np.int64)
        self._window_total = 0.0
        self._window_published = 0.0

    def _pop_pending_locked(self):
        """Drain the buffered stored-key puts as a uint32 ``[n, 5]`` id
        batch, or ``None`` when nothing is pending (callers hold the
        lock) — the one copy of the buffer→ids conversion both flush
        sites (the wave-riding one in :meth:`observe_ids`, the idle-node
        one in :meth:`tick`) share."""
        if not self._pending_store:
            return None
        from .ops.ids import ids_from_bytes
        stored = ids_from_bytes(b"".join(self._pending_store))
        self._pending_store = []
        # the store series counts at FLUSH time, so it matches what the
        # sketch/window actually saw — counting at buffer time credited
        # keys the store_buffer bound evicted (review finding)
        c = self._m_obs.get("store")
        if c is None:
            c = self._m_obs["store"] = telemetry.get_registry().counter(
                "dht_keyspace_observed_total", source="store",
                **self._labels)
        c.inc(int(stored.shape[0]))
        return stored

    # ------------------------------------------------------------ ingest
    def note_stored(self, key: InfoHash) -> None:
        """Record one stored-key put.  Buffered host-side and flushed
        into the NEXT wave's scatter-add launch (or the tick's flush) —
        stores never cost their own device launch."""
        if not self.enabled:
            return
        with self._lock:
            self._pending_store.append(bytes(key))
            drop = (len(self._pending_store)
                    - max(1, int(self.cfg.store_buffer)))
            if drop > 0:
                # drop-oldest: a windowed observatory keeps the RECENT
                # traffic when the buffer has no drain (tick=0, no waves)
                del self._pending_store[:drop]

    def observe_hashes(self, targets, source: str = "wave") -> None:
        """Observe a wave's target ids (:class:`InfoHash` iterable) —
        the ``runtime/wave_builder.py _launch`` hook."""
        if not targets or not self.enabled:
            return
        from .ops.ids import ids_from_hashes
        self.observe_ids(ids_from_hashes(targets), source=source)

    def observe_ids(self, ids, source: str = "wave") -> None:
        """Observe a batch of ids (uint32 ``[Q, 5]``, numpy or device):
        ONE async scatter-add launch updating sketch + histogram, plus
        O(Q/stride) host dict ops for candidate sampling.  Never
        blocks; never raises into the wave path."""
        if not self.enabled or not self._ensure_device():
            return
        try:
            arr = np.ascontiguousarray(np.asarray(ids, np.uint32)
                                       ).reshape(-1, N_LIMBS)
        except Exception:
            log.exception("keyspace observe: bad id batch")
            return
        if arr.size == 0:
            return
        with self._lock:
            stored = self._pop_pending_locked()
            full = (np.concatenate([arr, stored], axis=0)
                    if stored is not None else arr)
            try:
                from .ops import sketch as sk
                self._sketch, self._hist = sk.sketch_update(
                    self._sketch, self._hist, full)
            except Exception:
                log.exception("keyspace sketch update failed; disabling")
                self._go_dark_locked()
                dark = True
            else:
                dark = False
        if dark:
            self._export_gauges()       # gauges flip to unknown (-1)
            return
        with self._lock:
            n = int(full.shape[0])
            self._observed_total += n
            self._window_total += n
            self._since_tick += n
            self._admit_candidates_locked(full)
        c = self._m_obs.get(source)
        if c is None:
            with self._lock:
                c = self._m_obs.get(source)
                if c is None:
                    c = self._m_obs[source] = telemetry.get_registry(
                    ).counter("dht_keyspace_observed_total",
                              source=source, **self._labels)
        c.inc(int(arr.shape[0]))

    def _admit_candidates_locked(self, batch) -> None:
        """Sample-and-hold candidate admission over one observed batch
        (callers hold the lock): a round-robin phase over the stream —
        every stride-th id enters the candidate set, so a key with
        >= stride occurrences per window is admitted with
        near-certainty.  Shared by the wave path (:meth:`observe_ids`)
        and the tick's idle-node store flush — a hot stored key must be
        detectable whichever surface carried it (review finding)."""
        stride = max(1, int(self.cfg.sample_stride))
        start = (-self._sample_phase) % stride
        self._sample_phase = (self._sample_phase + len(batch)) % stride
        sampled = batch[start::stride]
        if not len(sampled):
            return
        from .ops.ids import ids_to_bytes
        cand = self._candidates
        # canonical big-endian 20-byte id form — the same bytes
        # note_stored buffers and InfoHash serializes, so the
        # re-score reconstructs EXACTLY the observed ids
        for row in ids_to_bytes(sampled):
            kb = row.tobytes()
            cand[kb] = cand.get(kb, 0) + 1
        if len(cand) > self.cfg.candidates:
            self._prune_candidates()

    def _prune_candidates(self) -> None:
        """Evict the coldest half by host hit count (callers hold the
        lock).  Current top-K keys are always retained — a hot key must
        not be evicted by a burst of one-hit wonders."""
        keep = set(t["_key"] for t in self._top)
        items = sorted(self._candidates.items(), key=lambda kv: -kv[1])
        limit = max(self.cfg.candidates // 2, self.cfg.top_k)
        kept = {}
        for kb, hits in items:
            if kb in keep or len(kept) < limit:
                kept[kb] = hits
        self._candidates = kept

    # -------------------------------------------------------- subscribers
    def subscribe(self, cb: Callable[[List[dict]], None]) -> None:
        """Register a tick subscriber (ISSUE-11): ``cb(top)`` fires
        after every tick that (re)publishes the heavy-hitter list —
        ``top`` entries carry the canonical ``_key`` bytes alongside
        the public fields, so an acting layer (the hot-value cache) can
        key device state off them.  A dark/disabled tick notifies with
        an empty list so subscribers narrow/evict instead of holding a
        stale hot set."""
        self._subscribers.append(cb)

    def _notify(self, top: List[dict]) -> None:
        for cb in self._subscribers:
            try:
                cb(top)
            except Exception:
                log.exception("keyspace tick subscriber failed")

    # --------------------------------------------------------------- tick
    def attach(self, scheduler) -> None:
        """Arm the periodic tick on the node scheduler (decay, heavy-
        hitter re-score, gauge refresh)."""
        if not self.enabled or self.cfg.tick <= 0 or self._job is not None:
            return
        self._sched = scheduler
        self._job = scheduler.add(scheduler.time() + self.cfg.tick,
                                  self._tick_job)

    def _tick_job(self) -> None:
        try:
            self.tick()
        except Exception:
            log.exception("keyspace tick failed")
        finally:
            self._job = self._sched.add(
                self._sched.time() + self.cfg.tick, self._tick_job)

    def tick(self) -> dict:
        """One observatory pass: re-score the candidate set against the
        sketch (one batched query launch), retain the top-K, emit
        ``hot_key_emerged`` for keys newly crossing the hot rule, fold
        the histogram into per-shard loads + the imbalance ratio,
        refresh the gauges, then decay the window.  Cheap no-op while
        nothing has been observed."""
        if not self.enabled or (self._device_ok is not True
                                and not (self._pending_store
                                         and self._ensure_device())):
            if self.enabled:
                # disabled observatories never register their gauge
                # series (the round-14 permanently-zero-series rule)
                self._export_gauges()
            return self.snapshot()
        from .ops import sketch as sk
        dark = False
        with self._lock:
            stored = self._pop_pending_locked()
            if stored is not None:
                # flush stores that no wave carried (idle node)
                try:
                    self._sketch, self._hist = sk.sketch_update(
                        self._sketch, self._hist, stored)
                except Exception:
                    # same go-dark contract as observe_ids: on an
                    # idle put-only node this flush is the SOLE device
                    # call, and a stale published window would hold
                    # the health signal on no evidence forever
                    log.exception("keyspace store flush failed; disabling")
                    self._go_dark_locked()
                    dark = True
                else:
                    self._window_total += stored.shape[0]
                    self._observed_total += stored.shape[0]
                    # admit BEFORE the candidate snapshot below so the
                    # flushed keys are re-scored this very tick
                    self._admit_candidates_locked(stored)
            dirty = self._since_tick > 0 or self._window_total > 0
            cand_keys = list(self._candidates)
            wt_seen = self._window_total
            sketch = self._sketch
            hist = self._hist
        if dark:
            self._export_gauges()       # gauges flip to unknown (-1)
            self._notify([])            # subscribers drop the hot set
            return self.snapshot()
        if not dirty:
            self._export_gauges()
            # quiet ticks still notify subscribers with the retained
            # top (ISSUE-11 review finding): the acting layers' windows
            # must roll and their TTL sweeps must run on an idle node —
            # a frozen hit-ratio window would hold the degrade-only
            # health signal (and dhtmon --min-cache-hit) on a stale
            # low ratio forever
            with self._lock:
                top = list(self._top)
            self._notify(top)
            return self.snapshot()
        # ---- heavy hitters: candidate re-score, ONE batched query
        top: List[dict] = []
        if cand_keys:
            from .ops.ids import ids_from_bytes
            ids = ids_from_bytes(b"".join(cand_keys))
            try:
                est = np.asarray(sk.sketch_query(sketch, ids))
            except Exception:
                log.exception("keyspace re-score failed; disabling")
                with self._lock:
                    self._go_dark_locked()
                self._export_gauges()   # gauges flip to unknown (-1)
                self._notify([])        # subscribers drop the hot set
                return self.snapshot()
            order = np.argsort(-est, kind="stable")[:self.cfg.top_k]
            wt = max(wt_seen, 1.0)
            for i in order:
                e = int(est[int(i)])
                if e <= 0:
                    continue
                kb = cand_keys[int(i)]
                share = e / wt
                top.append({
                    "key": kb.hex(), "_key": kb, "estimate": e,
                    "share": round(share, 4),
                    "hot": (share >= self.cfg.hot_share
                            and e >= self.cfg.hot_min_count),
                })
        # ---- shard loads off the histogram
        hist_host = np.asarray(hist, np.int64)
        t, edges, virtual = self._shard_edges()
        loads = fold_bins(hist_host, edges)
        total = float(hist_host.sum())
        imb = (_imbalance(loads)
               if total >= self.cfg.min_observed else None)
        # ---- publish + events
        tr = tracing.get_tracer()
        with self._lock:
            prev_hot = self._hot
            hot = set(t_["_key"] for t_ in top if t_["hot"])
            for t_ in top:
                if t_["hot"] and t_["_key"] not in prev_hot \
                        and tr.enabled:
                    tr.event("hot_key_emerged", node=self.node,
                             key=t_["key"], estimate=t_["estimate"],
                             share=t_["share"],
                             window_total=int(wt_seen))
            self._top = top
            self._hot = hot
            self._window_published = wt_seen
            self._loads = loads
            self._shard_t = t
            self._shard_virtual = virtual
            self._imbalance = imb
            self._hist_host = hist_host
            self._since_tick = 0
            # ---- decay: window, not lifetime
            if self.cfg.decay < 1.0:
                try:
                    self._sketch, self._hist = sk.sketch_decay(
                        self._sketch, self._hist, self.cfg.decay)
                except Exception:
                    # go-dark like every other device-call site: the
                    # products published just above are cleared rather
                    # than frozen at the last good window
                    log.exception("keyspace decay failed; disabling")
                    self._go_dark_locked()
                else:
                    self._window_total *= self.cfg.decay
                    if self._window_total < 1.0:
                        # a fully-decayed window goes quiet: later idle
                        # ticks are dict checks, not device launches
                        self._window_total = 0.0
                    for kb in list(self._candidates):
                        hits = self._candidates[kb] >> 1
                        if hits or kb in hot:
                            self._candidates[kb] = hits
                        else:
                            del self._candidates[kb]
            went_dark = self._device_ok is False
        self._export_gauges()
        # acting layers (the hot-value cache) see the SAME top list the
        # snapshot publishes — or an empty one if the decay launch went
        # dark (the published products were cleared with it)
        self._notify([] if went_dark else top)
        return self.snapshot()

    def _shard_edges(self) -> Tuple[int, List[float], bool]:
        """(t, interior bin edges, virtual): ``t > 0`` when a resolve
        mesh serves; ``virtual`` is False ONLY when the edges are the
        table's actual boundary ids — a mesh whose shard_info falls
        back (no snapshot yet, partially-filled table) folds over the
        uniform split and must say so, or the snapshot reports a
        uniform ring split as real per-shard loads (review
        finding).

        ``shard_info`` may return ``(t, bounds)`` or — since the
        reshard plane (ISSUE-17) — ``(t, bounds, virtual)``, where
        ``bounds`` is either boundary *ids* (uint limb rows) or
        pre-folded fractional *bin edges* (floats, the virtual
        resharded split).  Fold attribution always follows the edges
        of the CURRENT layout: frames recorded before a swap keep the
        values folded at their own tick (frames are immutable deltas),
        later ticks attribute to the new ownership."""
        if self._shard_info is not None:
            try:
                info = self._shard_info()
                t, bounds = info[0], info[1]
                virtual = info[2] if len(info) > 2 else None
                if t and t > 1:
                    if bounds is not None and len(bounds):
                        arr = np.asarray(bounds)
                        if arr.dtype.kind == "f":
                            edges = [float(x) for x in np.sort(arr)]
                            return t, edges, (True if virtual is None
                                              else bool(virtual))
                        return t, bin_edges_from_ids(bounds), (
                            False if virtual is None else bool(virtual))
                    return t, bin_edges_uniform(t), True
            except Exception:
                log.debug("keyspace shard_info failed", exc_info=True)
        t = max(2, int(self.cfg.virtual_shards))
        return 0, bin_edges_uniform(t), True

    def _export_gauges(self) -> None:
        reg = telemetry.get_registry()
        with self._lock:
            imb = self._imbalance
            top = self._top
            wt = self._window_published
            occupied = int(np.count_nonzero(self._hist_host))
            hot_n = len(self._hot)
        reg.gauge("dht_keyspace_window_total", **self._labels).set(wt)
        reg.gauge("dht_keyspace_occupied_bins", **self._labels).set(occupied)
        reg.gauge("dht_hotkey_count", **self._labels).set(hot_n)
        reg.gauge("dht_hotkey_top_estimate", **self._labels).set(
            top[0]["estimate"] if top else 0)
        # -1 = unknown (below min_observed), same convention as the
        # health signal gauges
        reg.gauge("dht_shard_imbalance", **self._labels).set(
            -1.0 if imb is None else imb)

    # ---------------------------------------------------------- read side
    def imbalance(self) -> Optional[float]:
        """Last tick's max/mean per-shard load ratio; None below
        ``min_observed`` windowed observations OR while the observatory
        is disabled/dark (unknown, not balanced) — the
        ``shard_imbalance`` health-signal provider."""
        if not self.enabled:
            return None
        return self._imbalance

    def hist_window(self):
        """Copy of the last published 256-bin windowed load histogram
        (int64, top-8-bit key space) — the reshard tick's solver input
        (opendht_tpu/reshard.py): boundaries are solved from the SAME
        fold space the imbalance gauge measures in."""
        with self._lock:
            return np.array(self._hist_host, np.int64, copy=True)

    def top_keys(self) -> List[dict]:
        """Last tick's heavy hitters (key hex, windowed estimate,
        share, hot flag)."""
        with self._lock:
            return [{k: v for k, v in t.items() if k != "_key"}
                    for t in self._top]

    def snapshot(self) -> dict:
        """JSON-able observatory state — the proxy ``GET /keyspace``
        body, the ``keyspace`` REPL command and the scanner section."""
        with self._lock:
            imb = self._imbalance
            loads = list(self._loads)
            t = self._shard_t
            virtual = self._shard_virtual
            hist = self._hist_host.tolist()
            top = [{k: v for k, v in t_.items() if k != "_key"}
                   for t_ in self._top]
            wt = self._window_published
            lifetime = self._observed_total
            cands = len(self._candidates)
        return {
            "enabled": bool(self.enabled),
            "depth": self.cfg.depth,
            "width": self.cfg.width,
            "decay": self.cfg.decay,
            "tick_s": self.cfg.tick,
            "observed_total": int(lifetime),
            "window_total": round(wt, 1),
            "candidates": cands,
            "hist_bins": BINS,
            "hist": hist,
            "occupied_bins": int(sum(1 for c in hist if c)),
            "top": top,
            "hot_keys": [t_["key"] for t_ in top if t_["hot"]],
            "shards": {
                # t == 0: no live resolve mesh.  virtual: the loads
                # attribute to a uniform ring split (what a t-way
                # sharding WOULD see) — also True for a LIVE mesh whose
                # shard_info fell back (no snapshot / partial fill)
                "t": t,
                "virtual": virtual,
                "n": len(loads),
                "loads": [round(x, 2) for x in loads],
                "imbalance": (round(imb, 4) if imb is not None else None),
            },
        }
