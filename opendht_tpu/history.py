"""Time-series flight data recorder: retained metrics history (round 17).

Six observability surfaces (``/stats``, ``/trace``, ``/healthz``,
``/keyspace``, ``/cache``, the kernel ledger) are all point-in-time:
``dhtmon --window`` fakes a window by scraping twice and waiting, the
round-14 SLO engine re-derives every burn rate from private
prior-snapshot state, and when a node goes unhealthy the evidence is
gone by the time anyone looks.  The reference keeps only instants too
(``Dht::dumpTables`` / ``getNodesStats``) — retained history is the
capability a serving stack adds on top, and the substrate the ROADMAP's
load-aware resharding hysteresis ("driven by *measured* traffic") and
swarm soaks need.  This module is that retention layer:

- :class:`MetricsHistory` — a bounded in-memory ring
  (``deque(maxlen=capacity)``, oldest-evicted) of periodic,
  **delta-encoded** registry frames, ticking on the node scheduler
  exactly like the round-14 health tick (host-side snapshot
  subtraction only — no device work, kernels bit-identical with the
  tick on, pinned by benchmarks/exp_history_r17.py).  Per frame:
  counters as deltas vs the previous tick, histograms as bucket deltas
  (via the round-8 :meth:`telemetry.Histogram.raw` contract), gauges
  as last-value recorded only when they changed.  Series keys use the
  Prometheus form ``name{k="v"}`` — the SAME names ``GET /stats``
  exports, so frame sums and scrape diffs are directly comparable.
- **Windowed queries**: :meth:`~MetricsHistory.rate` /
  :meth:`~MetricsHistory.counter_delta` /
  :meth:`~MetricsHistory.quantile` over any ``(t0, t1]`` window the
  ring still covers, reusing :func:`telemetry.quantile_from_buckets`
  (the ONE interpolation copy, round-15 consolidation).  The round-14
  health evaluator reads its SLO windows through these when a recorder
  is attached instead of keeping private ``_Window`` state — one delta
  codepath (opendht_tpu/health.py).
- **Bounded on-disk spill** (optional, ``spill_dir``): frames append to
  segment files of ``spill_segment_frames`` JSON lines each; at most
  ``spill_max_segments`` segments are retained, oldest deleted first —
  RSS *and* disk stay stable under a flood
  (testing/history_smoke.py soak-checks a 10x flood).
- **Post-mortem black-box bundles**: :func:`build_bundle` assembles
  the last N frames + the round-9 flight-recorder ring (spans AND
  events) + kernel ledger + keyspace/cache/ingest snapshots + the
  health report into ONE JSON artifact.  ``runtime/runner.py`` captures
  one automatically on every ``health_transition`` to unhealthy (the
  evidence survives the incident) and serves fresh ones via
  ``DhtRunner.dump_bundle()`` / proxy ``GET /debug/bundle`` / the
  ``bundle`` REPL cmd / ``dhtscanner --bundle DIR``; captured bundles
  are retained in a second bounded ring (``retain_bundles``).
- **Cluster timelines**: testing/timeline_assembler.py merges per-node
  histories/bundles (scrape-timestamp skew estimate,
  monotonicity-checked like the round-9 trace assembler) so
  ``dhtmon --since`` gates on real windowed invariants instead of
  scrape-diff-scrape.

Import-light by design (stdlib + the telemetry/tracing spine) so the
recorder runs in minimal containers and pure-registry unit tests.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time as _time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from . import telemetry, tracing
from .telemetry import _bucket_le, _fmt, _series_name

log = logging.getLogger("opendht_tpu.history")

__all__ = [
    "HistoryConfig", "MetricsHistory", "build_bundle", "frames_to_series",
    "BUNDLE_KIND",
]

#: the ``kind`` tag every black-box bundle carries (consumers dispatch
#: on it; the timeline assembler accepts bundles by this tag)
BUNDLE_KIND = "dht-blackbox-bundle"

#: spill segment file name pattern (sortable by sequence number)
_SEG_FMT = "frames-%08d.jsonl"
_SEG_PREFIX = "frames-"


@dataclass
class HistoryConfig:
    """Declarative recorder configuration (lives on
    ``runtime.config.Config.history``)."""

    #: seconds between recorder ticks on the node scheduler; 0 = the
    #: runner never attaches a recorder (history surfaces report
    #: ``enabled: false`` and the health engine keeps its private
    #: windows)
    period: float = 1.0
    #: frames retained in the in-memory ring (oldest evicted).  At the
    #: default 1 s period 768 frames cover ~12.8 minutes — past the
    #: health engine's slow SLO window (600 s) WITH the same 1.25x
    #: slack its private ``_Window`` kept (a shorter ring would
    #: silently truncate the slow window to partial totals).  Scale
    #: capacity >= slow_window / period when shrinking the period.
    capacity: int = 768
    #: frames embedded in a black-box bundle (the "last N" the
    #: post-mortem needs; <= capacity)
    bundle_frames: int = 120
    #: auto-captured bundles retained (a flapping node must not hold
    #: unbounded evidence)
    retain_bundles: int = 4
    #: optional on-disk spill directory ("" = in-memory only)
    spill_dir: str = ""
    #: frames per spill segment file
    spill_segment_frames: int = 128
    #: segment files retained (oldest deleted) — disk is bounded by
    #: ``spill_max_segments * spill_segment_frames`` frames
    spill_max_segments: int = 8


def _norm_buckets(buckets) -> Dict[int, float]:
    """Bucket maps round-trip through JSON (proxy, bundle files, spill
    segments) where dict keys become strings — normalize back to int
    indices so every reader sees one shape."""
    return {int(k): v for k, v in buckets.items()}


class MetricsHistory:
    """The bounded ring of delta-encoded registry frames (see module
    docstring).  ``tick()`` is cheap host-side subtraction; queries are
    safe from any thread (proxy handlers read while the DHT thread
    ticks)."""

    def __init__(self, cfg: Optional[HistoryConfig] = None, *,
                 registry: Optional[telemetry.MetricsRegistry] = None,
                 clock: Callable[[], float] = _time.monotonic,
                 node: str = ""):
        self.cfg = cfg or HistoryConfig()
        self.reg = registry or telemetry.get_registry()
        self.clock = clock
        self.node = node
        self.enabled = self.cfg.period > 0 and self.cfg.capacity > 0
        #: serializes whole ticks (sample + commit).  Sampling happens
        #: outside ``_lock`` so readers aren't blocked behind registry
        #: walks, but two concurrent ticks (the scheduler job + a test
        #: or smoke calling tick() directly) could then commit samples
        #: out of order and the counter-reset heuristic would replay
        #: full cumulative values as one frame's delta (review
        #: finding) — the tick lock makes sample→commit atomic per
        #: tick while ``_lock`` alone still guards reader access.
        self._tick_lock = threading.Lock()
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(int(self.cfg.capacity), 1))
        self._bundles: deque = deque(maxlen=max(
            int(self.cfg.retain_bundles), 1))
        self._seq = 0
        self._prev_mono: Optional[float] = None
        # series name -> cumulative baseline (counters: value;
        # histograms: (count, sum, {bucket: count}); gauges: last value)
        self._prev_counters: Dict[str, float] = {}
        self._prev_hists: Dict[str, tuple] = {}
        self._prev_gauges: Dict[str, float] = {}
        # spill state
        self._spill_buf: List[dict] = []
        self._spill_seq = 0
        self._spill_failed = False
        self._job = None
        # frame hooks (round 22): callables invoked with each committed
        # frame, AFTER the ring append and outside the reader lock —
        # the windowed-reset spine (pipeline observatory occupancy
        # checkpoints, the wave builder's windowed in-flight peak)
        self._frame_hooks: List[Callable[[dict], None]] = []
        # export handles (cached like the scheduler's)
        self._m_frames = self.reg.gauge("dht_history_frames",
                                        **({"node": node} if node else {}))
        self._m_ticks = self.reg.counter("dht_history_ticks_total",
                                         **({"node": node} if node else {}))

    # ------------------------------------------------------------ sampling
    def _sample(self) -> tuple:
        """One consistent-enough pass over the registry: cumulative
        counter/gauge values and histogram raw() triples, keyed by the
        Prometheus series name.  Reads only the non-mutating accessors
        (``families``/``series``) — the get-or-create factories would
        register ghost series (round-14 review finding)."""
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        hists: Dict[str, tuple] = {}
        for name, kind in self.reg.families().items():
            for key, m in self.reg.series(name).items():
                sname = _series_name(name, key)
                if kind == "counter":
                    counters[sname] = m.value
                elif kind == "gauge":
                    gauges[sname] = m.value
                else:
                    hists[sname] = m.raw()
        return counters, gauges, hists

    # ---------------------------------------------------------------- tick
    def tick(self, now: Optional[float] = None) -> Optional[dict]:
        """One recording pass: delta the registry against the previous
        tick's cumulative sample and append a frame.  The FIRST tick
        only establishes the baseline (a frame diffing against process
        zero would report the node's whole lifetime as one window).
        Returns the appended frame, or None (first tick / disabled)."""
        if not self.enabled:
            return None
        with self._tick_lock:
            return self._tick_inner(now)

    def _tick_inner(self, now: Optional[float]) -> Optional[dict]:
        now = self.clock() if now is None else now
        counters, gauges, hists = self._sample()
        spill_batch = None
        with self._lock:
            first = self._prev_mono is None
            frame = None
            if not first:
                frame = self._delta_frame_locked(now, counters, gauges,
                                                 hists)
                self._ring.append(frame)
                if self.cfg.spill_dir and not self._spill_failed:
                    self._spill_buf.append(frame)
                    if len(self._spill_buf) >= max(
                            self.cfg.spill_segment_frames, 1):
                        spill_batch = (self._spill_buf, self._spill_seq)
                        self._spill_buf = []
                        self._spill_seq += 1
            self._prev_mono = now
            self._prev_counters = counters
            self._prev_gauges = gauges
            self._prev_hists = hists
            nframes = len(self._ring)
        if spill_batch is not None:
            # disk I/O OUTSIDE the lock: a slow disk must not stall the
            # scheduler thread against concurrent proxy/health readers
            self._write_segment(*spill_batch)
        if frame is not None:
            for fn in list(self._frame_hooks):
                try:
                    fn(frame)
                except Exception:
                    log.exception("history frame hook failed")
        self._m_frames.set(nframes)
        self._m_ticks.inc()
        return frame

    def add_frame_hook(self, fn: Callable[[dict], None]) -> None:
        """Register ``fn(frame)`` to run after every committed frame
        (outside the reader lock, on the ticking thread).  This is the
        recorder's windowed-reset cadence: per-frame windows elsewhere
        (pipeline occupancy checkpoints, the windowed in-flight peak)
        key off it instead of inventing their own timers.  Exceptions
        are logged and swallowed — a broken hook must not stop the
        flight recorder."""
        self._frame_hooks.append(fn)

    def _delta_frame_locked(self, now: float, counters, gauges,
                            hists) -> dict:
        dcounters: Dict[str, float] = {}
        for k, v in counters.items():
            d = v - self._prev_counters.get(k, 0)
            if d < 0:           # counter reset (tests zero in place):
                d = v           # the new value IS the window's events
            if d:
                dcounters[k] = d
        dgauges = {k: v for k, v in gauges.items()
                   if self._prev_gauges.get(k) != v}
        dhists: Dict[str, dict] = {}
        for k, (count, total, buckets) in hists.items():
            pc, ps, pb = self._prev_hists.get(k, (0, 0.0, {}))
            dc = count - pc
            if dc < 0:          # histogram reset
                dc, ds = count, total
                db = dict(buckets)
            else:
                ds = total - ps
                db = {}
                for i in set(buckets) | set(pb):
                    d = buckets.get(i, 0) - pb.get(i, 0)
                    if d:
                        db[i] = d
            if dc:
                dhists[k] = {"count": dc, "sum": ds, "buckets": db}
        self._seq += 1
        return {
            "seq": self._seq,
            "t": _time.time(),
            "mono": now,
            "dur": max(now - (self._prev_mono or now), 0.0),
            "counters": dcounters,
            "gauges": dgauges,
            "hist": dhists,
        }

    # ------------------------------------------------------------- spill
    def _write_segment(self, buf: List[dict], seq: int) -> None:
        """Write one full segment + prune old ones — called WITHOUT the
        lock (frames are immutable once appended; only the tick thread
        writes segments, so sequencing is single-writer)."""
        try:
            os.makedirs(self.cfg.spill_dir, exist_ok=True)
            path = os.path.join(self.cfg.spill_dir, _SEG_FMT % seq)
            with open(path, "w") as fh:
                for f in buf:
                    fh.write(json.dumps(f) + "\n")
            self._prune_segments()
        except OSError:
            # spill must never kill the tick — disable, keep the ring
            self._spill_failed = True
            log.exception("history spill failed; disabling spill")

    def _segment_paths(self) -> List[str]:
        try:
            names = sorted(n for n in os.listdir(self.cfg.spill_dir)
                           if n.startswith(_SEG_PREFIX)
                           and n.endswith(".jsonl"))
        except OSError:
            return []
        return [os.path.join(self.cfg.spill_dir, n) for n in names]

    def _prune_segments(self) -> None:
        paths = self._segment_paths()
        keep = max(int(self.cfg.spill_max_segments), 1)
        for p in paths[:-keep] if len(paths) > keep else []:
            try:
                os.remove(p)
            except OSError:
                pass

    def spilled_frames(self) -> List[dict]:
        """Every frame still on disk, oldest first (post-mortem read
        path; segments beyond ``spill_max_segments`` are gone)."""
        paths = self._segment_paths() if self.cfg.spill_dir else []
        out: List[dict] = []
        for p in paths:
            try:
                with open(p) as fh:
                    for ln in fh:
                        if ln.strip():
                            out.append(json.loads(ln))
            except (OSError, ValueError):
                continue
        return out

    @property
    def spill_segments(self) -> int:
        return len(self._segment_paths()) if self.cfg.spill_dir else 0

    # ------------------------------------------------------------ queries
    def frames(self, t0: Optional[float] = None,
               t1: Optional[float] = None,
               limit: Optional[int] = None) -> List[dict]:
        """Frames with ``mono`` in ``(t0, t1]`` (None = unbounded),
        oldest first; ``limit`` keeps the newest N (0 = none — not
        "unlimited", matching the proxy routes' limit contract)."""
        with self._lock:
            out = [f for f in self._ring
                   if (t0 is None or f["mono"] > t0)
                   and (t1 is None or f["mono"] <= t1)]
        if limit is None:
            return out
        return out[-limit:] if limit > 0 else []

    def _matching(self, table: dict, name: str):
        """Series of ``table`` whose name is exactly ``name`` or a
        labeled member of the ``name`` family.  A fully-labeled name
        (contains ``{``) is one dict hit — the health evaluator's
        exact-series queries must not pay a per-frame linear scan."""
        v = table.get(name)
        if v is not None:
            yield v
        if "{" in name:
            return
        pref = name + "{"
        for k, v in table.items():
            if k.startswith(pref):
                yield v

    def counter_delta(self, name: str, t0: float,
                      t1: float) -> Optional[float]:
        """Summed counter delta of one series (or a whole family) over
        the window; None when NO frame covers ``(t0, t1]`` (the window
        is not computable yet — the round-14 ``_Window`` contract)."""
        frames = self.frames(t0, t1)
        if not frames:
            return None
        total = 0.0
        for f in frames:
            for v in self._matching(f.get("counters") or {}, name):
                total += v
        return total

    def hist_delta(self, name: str, t0: float,
                   t1: float) -> Optional[Tuple[float, float, Dict[int, float]]]:
        """Merged ``(count, sum, {bucket_index: count})`` histogram
        delta over the window; None when no frame covers it."""
        frames = self.frames(t0, t1)
        if not frames:
            return None
        count, total = 0.0, 0.0
        buckets: Dict[int, float] = {}
        for f in frames:
            for h in self._matching(f.get("hist") or {}, name):
                count += h.get("count", 0)
                total += h.get("sum", 0.0)
                for i, c in _norm_buckets(h.get("buckets") or {}).items():
                    buckets[i] = buckets.get(i, 0) + c
        return count, total, buckets

    def rate(self, name: str, t0: float, t1: float) -> Optional[float]:
        """Per-second rate of a counter series/family over the window:
        summed deltas / covered seconds.  None with no coverage."""
        frames = self.frames(t0, t1)
        if not frames:
            return None
        span = sum(f.get("dur", 0.0) for f in frames)
        if span <= 0:
            return None
        total = 0.0
        for f in frames:
            for v in self._matching(f.get("counters") or {}, name):
                total += v
        return total / span

    def quantile(self, name: str, q: float, t0: float,
                 t1: float) -> Optional[float]:
        """Windowed quantile of a histogram series/family — the SAME
        interpolator as :meth:`telemetry.Histogram.quantile` (one
        shared copy, :func:`telemetry.quantile_from_buckets`); None
        when the window saw nothing."""
        d = self.hist_delta(name, t0, t1)
        if d is None:
            return None
        _count, _sum, buckets = d
        items = sorted((i, c) for i, c in buckets.items() if c > 0)
        total = sum(c for _i, c in items)
        if total <= 0:
            return None
        return telemetry.quantile_from_buckets(items, total, q)

    # ------------------------------------------------------------ bundles
    def store_bundle(self, bundle: dict) -> None:
        """Retain one captured bundle (bounded: ``retain_bundles``,
        oldest evicted — a flapping node cannot hoard evidence)."""
        with self._lock:
            self._bundles.append(bundle)

    def bundles(self) -> List[dict]:
        with self._lock:
            return list(self._bundles)

    # -------------------------------------------------------------- meta
    def meta(self) -> dict:
        """JSON-able recorder state (embedded by ``GET /history`` and
        the bundles).  The spill listdir happens OUTSIDE the lock — a
        hung filesystem must not let a proxy scrape stall the
        scheduler tick thread (review finding, same hazard as the
        segment writes)."""
        segments = len(self._segment_paths()) if self.cfg.spill_dir else 0
        with self._lock:
            return {
                "enabled": self.enabled,
                "period": self.cfg.period,
                "capacity": self.cfg.capacity,
                "frames_held": len(self._ring),
                "bundles_held": len(self._bundles),
                "spill": {
                    "dir": self.cfg.spill_dir,
                    "active": bool(self.cfg.spill_dir
                                   and not self._spill_failed),
                    "segments": segments,
                    "segment_frames": self.cfg.spill_segment_frames,
                    "max_segments": self.cfg.spill_max_segments,
                },
            }

    # ---------------------------------------------------------- scheduling
    def attach(self, scheduler) -> None:
        """Schedule the periodic recording tick on the node scheduler
        (the round-14 NodeHealth attach pattern)."""
        if not self.enabled or self._job is not None:
            return
        self._sched = scheduler
        self._job = scheduler.add(scheduler.time() + self.cfg.period,
                                  self._tick_job)

    def _tick_job(self) -> None:
        try:
            self.tick()
        finally:
            self._job = self._sched.add(
                self._sched.time() + self.cfg.period, self._tick_job)


# ================================================== frame -> series view
def frames_to_series(frames: List[dict]) -> Dict[str, float]:
    """Sum a frame sequence into the SAME ``{series: value}`` shape
    ``testing/health_monitor.parse_exposition`` produces from a
    ``GET /stats`` scrape — counters as summed deltas, histogram
    buckets expanded to cumulative ``<family>_bucket{...,le="X"}``
    entries (plus ``_count``).  This is what lets ``dhtmon`` evaluate
    its windowed invariants (``lookup_success`` / ``cluster_quantile``)
    over history frames through the EXACT code path the scrape-diff
    mode uses — one delta codepath, pinned equal in
    tests/test_history.py."""
    out: Dict[str, float] = {}
    hist_acc: Dict[str, Dict[int, float]] = {}
    hist_count: Dict[str, float] = {}
    for f in frames:
        for k, v in (f.get("counters") or {}).items():
            out[k] = out.get(k, 0.0) + v
        for k, h in (f.get("hist") or {}).items():
            acc = hist_acc.setdefault(k, {})
            for i, c in _norm_buckets(h.get("buckets") or {}).items():
                acc[i] = acc.get(i, 0) + c
            hist_count[k] = hist_count.get(k, 0.0) + h.get("count", 0)
    for k, acc in hist_acc.items():
        family, _, rest = k.partition("{")
        labels = rest[:-1] if rest else ""
        cum = 0.0
        for i in sorted(acc):
            cum += acc[i]
            inner = (labels + "," if labels else "") + \
                'le="%s"' % _fmt(_bucket_le(i))
            out["%s_bucket{%s}" % (family, inner)] = cum
        out[family + "_count" + ("{%s}" % labels if labels else "")] = \
            hist_count.get(k, cum)
    return out


# ====================================================== bundle assembly
def build_bundle(*, reason: str = "on_demand", node_id: str = "",
                 status: str = "", history: Optional[MetricsHistory] = None,
                 health: Optional[dict] = None,
                 metrics: Optional[dict] = None,
                 keyspace: Optional[dict] = None,
                 cache: Optional[dict] = None,
                 ingest: Optional[dict] = None,
                 waterfall: Optional[dict] = None,
                 pipeline: Optional[dict] = None,
                 peers: Optional[dict] = None,
                 listeners: Optional[dict] = None,
                 tracer: Optional[tracing.Tracer] = None,
                 flight_limit: int = 400) -> dict:
    """Assemble one post-mortem black-box bundle (↔ the reference's
    ``Dht::dumpTables`` as a retained failure artifact): every section
    degrades to empty rather than raising — a half-up node must still
    bundle.  ``runtime/runner.py dump_bundle`` is the canonical
    caller; the sections are keyword-injected so tests and the smoke
    can bundle standalone recorders."""
    tr = tracer or tracing.get_tracer()
    bundle: dict = {
        "kind": BUNDLE_KIND,
        "schema": 1,
        "time": _time.time(),
        "reason": reason,
        "node_id": node_id,
        "status": status,
        "health": health or {},
        "metrics": metrics or {},
        "keyspace": keyspace or {},
        "cache": cache or {},
        "ingest": ingest or {},
        "waterfall": waterfall or {},
        "pipeline": pipeline or {},
        "peers": peers or {},
        "listeners": listeners or {},
        "history": {"enabled": False, "frames": []},
        "flight_recorder": {"spans": [], "events": []},
        "kernels": {},
        "auto_captures": [],
    }
    if history is not None:
        meta = history.meta()
        frames = history.frames(limit=history.cfg.bundle_frames)
        meta["frames"] = frames
        bundle["history"] = meta
        bundle["auto_captures"] = [
            {"time": b.get("time"), "reason": b.get("reason"),
             "transition": b.get("transition")}
            for b in history.bundles()]
    try:
        d = tr.dump()
        bundle["flight_recorder"] = {
            "node": d.get("node", ""),
            "capacity": d.get("capacity", 0),
            "spans": d.get("spans", [])[-flight_limit:],
            "events": d.get("events", [])[-flight_limit:],
        }
    except Exception:
        pass
    try:
        from . import profiling
        if profiling.ledger_computed():
            bundle["kernels"] = profiling.get_ledger().snapshot()
    except Exception:
        pass
    return bundle
