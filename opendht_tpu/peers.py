"""Per-peer network observatory (round 23, ISSUE-19).

Rounds 15-22 instrumented everything *inside* a node; the wire between
nodes stayed dark: ``dht_net_rtt_seconds{type=}`` aggregates over all
peers, and every retransmit fires at the fixed
``MAX_RESPONSE_TIME = 1.0`` regardless of whether the peer answers in
2 ms or 800 ms.  The reference keeps exactly this state per remote
node — ``net::Node``'s reply/time bookkeeping behind
``isGood``/dubious/expired (node.h:79-92) and the good/dubious counts
``getNodesStats`` folds over the routing table — but never closes the
loop into the retransmit timer.

:class:`PeerLedger` is a bounded LRU ledger keyed by (node id,
sockaddr), fed from the request lifecycle seams in
:mod:`~opendht_tpu.net.engine` / :mod:`~opendht_tpu.net.request`:

* **RTT estimator** — Jacobson/Karels EWMA + mean deviation per peer
  (RFC 6298 coefficients: srtt <- 7/8*srtt + 1/8*rtt, rttvar <-
  3/4*rttvar + 1/4*|srtt - rtt|), sampled under Karn's rule (only
  replies to never-retransmitted attempts; a reply after a retransmit
  is ambiguous about which attempt it answers).  Karn's *algorithm* is
  both halves: the sampling rule alone deadlocks when a link degrades
  after fast samples (every reply then follows a retransmit, so no
  sample can ever raise the estimate), so each timeout also doubles a
  per-peer backoff that multiplies the RTO until the next clean sample
  resets it (RFC 6298 §5.5-5.7).
* **Adaptive per-peer RTO** — ``srtt + 4*rttvar`` clamped to
  ``[rto_min, rto_max]``, consulted by ``Request.is_expired`` and the
  engine's retransmit wakeup scheduling when
  :attr:`PeersConfig.adaptive_rto` is on.  With zero RTT samples (or
  the knob off, or the ledger disabled) :meth:`PeerLedger.rto` returns
  exactly ``MAX_RESPONSE_TIME`` — the fixed-timeout path is the
  structural escape hatch and the no-sample behaviour is pinned
  equivalent (tests/test_peers.py).  ``rto_max`` defaults to
  ``MAX_ATTEMPT_COUNT * MAX_RESPONSE_TIME`` (the fixed path's total
  per-request patience): a high-variance link needs a per-attempt RTO
  *above* the fixed 1 s ceiling or the 4*rttvar term could never
  prevent the spurious retransmits it exists to prevent; a dead peer
  is still declared expired within the same order of patience the
  fixed path spends across its three attempts.  Set ``rto_max = 1.0``
  for a strict ``[rto_min, MAX_RESPONSE_TIME]`` clamp.
* **Attribution counts** — per-peer sent / completed / expired /
  cancelled requests, per-attempt retransmit timeouts, spurious
  retransmits (retransmissions of requests that ultimately completed:
  the reply was already in flight), bytes in/out by message type, and
  good<->dubious<->expired status flap transitions mirroring the
  reference's ``Node`` liveness rules.

The ledger is pure observation on the send/receive path: it never
composes packets, so wire bytes stay bit-identical with it enabled
(pinned by benchmarks/exp_peers_r23.py, which also commits the <1%
host-overhead paired delta as ``captures/peers_overhead.json``).

Exports: per-peer gauges ``dht_peer_srtt_seconds{peer=}`` /
``dht_peer_rto_seconds{peer=}`` / ``dht_peer_fail_ratio{peer=}``, a
per-peer histogram ``dht_peer_rtt_seconds{peer=}`` (the substrate
testing/network_monitor.py folds instead of its old roundtrip-only
view), aggregate ``dht_peer_tracked`` / ``dht_peer_evicted_total`` /
``dht_peer_flaps_total`` / ``dht_peer_spurious_retransmits_total`` /
``dht_peer_bytes_total{direction=,type=}``.  Everything is a plain
registry series, so it rides ``get_metrics()``, proxy ``GET /stats``
and the PR-12 history ring with no extra plumbing; the structured
:meth:`PeerLedger.snapshot` backs ``GET /peers``, the dhtnode REPL
``peers`` command, the dhtscanner ``peers`` section and the
testing/wiremap_assembler.py cluster wire map.  Evicted peers' gauges
are parked at ``-1`` (the registry has no removal API); every
per-peer reader treats negative values as unknown — the
``dhtmon --max-peer-fail`` contract.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Dict, Optional

from . import telemetry
from .net.node import MAX_RESPONSE_TIME

#: total patience of the fixed-timeout path (3 attempts x 1 s) — the
#: default per-attempt RTO ceiling, see the module docstring
_FIXED_PATIENCE = 3 * MAX_RESPONSE_TIME


@dataclass
class PeersConfig:
    """Per-peer observatory knobs (``Config.peers``)."""

    #: master switch; off = no ledger, no per-peer series, the engine
    #: and request lifecycle behave byte- and timing-identically to
    #: pre-round-23 builds
    enabled: bool = True
    #: LRU bound on tracked peers; the oldest-touched record is
    #: evicted past it (its gauges park at -1 = unknown)
    capacity: int = 256
    #: consult the Jacobson/Karels estimate for retransmit scheduling
    #: and request expiry.  Off (the default this round) keeps the
    #: fixed ``MAX_RESPONSE_TIME`` timetable everywhere — the ledger
    #: still *measures* per-peer RTT/RTO so operators can inspect the
    #: adaptive timer on the surfaces before opting in.
    adaptive_rto: bool = False
    #: lower clamp on the adaptive RTO: never retransmit faster than
    #: this even to a 2 ms peer (a reply delayed by one scheduler tick
    #: must not look like loss)
    rto_min: float = 0.25
    #: upper clamp on the adaptive RTO (default: the fixed path's
    #: total 3 x MAX_RESPONSE_TIME patience; 1.0 = strict
    #: [rto_min, MAX_RESPONSE_TIME])
    rto_max: float = _FIXED_PATIENCE
    #: a peer's fail ratio joins the ``peer_flap`` health signal and
    #: the dhtmon gate only after this many requests (one timed-out
    #: bootstrap ping is not a bad link)
    min_signal_events: int = 8


class PeerRecord:
    """One tracked remote peer (the ledger's LRU value)."""

    __slots__ = (
        "id", "addr", "label", "srtt", "rttvar", "samples", "backoff",
        "sent", "completed", "expired", "cancelled",
        "attempt_timeouts", "spurious_retrans",
        "bytes_in", "bytes_out", "msgs_in",
        "status", "flaps", "transitions", "first_seen", "last_seen",
        "_g_srtt", "_g_rto", "_g_fail", "_h_rtt",
    )

    def __init__(self, peer_id: str, addr: str, now: float):
        self.id = peer_id
        self.addr = addr
        # short-id@addr: unique per ledger key, short enough for label
        # cardinality sanity ("" id = anonymous bootstrap target)
        self.label = "%s@%s" % (peer_id[:8] or "?", addr)
        self.srtt: Optional[float] = None
        self.rttvar: Optional[float] = None
        self.samples = 0
        self.backoff = 0          # Karn backoff exponent (doublings)
        self.sent = 0             # requests (first attempts)
        self.completed = 0
        self.expired = 0          # requests that ran out of attempts
        self.cancelled = 0
        self.attempt_timeouts = 0  # retransmissions (per-attempt)
        self.spurious_retrans = 0  # retransmits of requests that completed
        self.bytes_in: Dict[str, int] = {}
        self.bytes_out: Dict[str, int] = {}
        self.msgs_in = 0
        self.status: Optional[str] = None   # good | dubious | expired
        self.flaps = 0
        self.transitions: Dict[str, int] = {}
        self.first_seen = now
        self.last_seen = now
        self._g_srtt = None
        self._g_rto = None
        self._g_fail = None
        self._h_rtt = None

    def fail_ratio(self) -> Optional[float]:
        """Expired fraction of finished requests; None below two
        finished requests (nothing to attribute yet)."""
        done = self.completed + self.expired
        if done <= 0:
            return None
        return self.expired / done

    def to_doc(self, rto: float) -> dict:
        return {
            "id": self.id, "addr": self.addr, "peer": self.label,
            "srtt": self.srtt, "rttvar": self.rttvar, "rto": rto,
            "samples": self.samples, "backoff": self.backoff,
            "sent": self.sent, "completed": self.completed,
            "expired": self.expired, "cancelled": self.cancelled,
            "attempt_timeouts": self.attempt_timeouts,
            "spurious_retransmits": self.spurious_retrans,
            "fail_ratio": self.fail_ratio(),
            "bytes_in": dict(self.bytes_in),
            "bytes_out": dict(self.bytes_out),
            "msgs_in": self.msgs_in,
            "status": self.status, "flaps": self.flaps,
            "transitions": dict(self.transitions),
            "first_seen": self.first_seen, "last_seen": self.last_seen,
        }


class PeerLedger:
    """Bounded per-peer ledger; every hook is O(1) host arithmetic
    under one lock (the engine is single-threaded under the scheduler,
    but proxy handler threads call :meth:`snapshot` concurrently)."""

    def __init__(self, cfg: Optional[PeersConfig] = None, node: str = "",
                 clock=None, registry=None):
        self.cfg = cfg or PeersConfig()
        self.enabled = bool(self.cfg.enabled)
        self.node = node
        self._clock = clock or (lambda: 0.0)
        self.reg = registry or telemetry.get_registry()
        self._lock = threading.Lock()
        self._peers: "OrderedDict[tuple, PeerRecord]" = OrderedDict()
        self.evicted = 0
        self._g_tracked = self.reg.gauge("dht_peer_tracked",
                                         node=node)
        self._c_evicted = self.reg.counter("dht_peer_evicted_total",
                                           node=node)
        self._c_flaps = self.reg.counter("dht_peer_flaps_total", node=node)
        self._c_spurious = self.reg.counter(
            "dht_peer_spurious_retransmits_total", node=node)
        self._m_bytes: Dict[tuple, telemetry.Counter] = {}

    # ------------------------------------------------------------- records
    @staticmethod
    def _key(node) -> tuple:
        return (str(node.id) if node.id else "", str(node.addr))

    def _rec(self, node, now: float) -> PeerRecord:
        """Get-or-create + LRU touch; caller holds the lock."""
        key = self._key(node)
        rec = self._peers.get(key)
        if rec is None:
            rec = PeerRecord(key[0], key[1], now)
            self._peers[key] = rec
            while len(self._peers) > max(self.cfg.capacity, 1):
                _, old = self._peers.popitem(last=False)
                self.evicted += 1
                self._c_evicted.inc()
                # park the evicted peer's gauges at the unknown
                # sentinel — no removal API, and every reader
                # (dhtmon/wiremap/health) filters v < 0
                for g in (old._g_srtt, old._g_rto, old._g_fail):
                    if g is not None:
                        g.set(-1.0)
            self._g_tracked.set(float(len(self._peers)))
        else:
            self._peers.move_to_end(key)
        rec.last_seen = now
        return rec

    def _refresh_status(self, rec: PeerRecord, node, now: float) -> None:
        """Mirror the reference's Node liveness classification
        (node.h:79-92) into the ledger and count flap transitions."""
        if node.expired:
            st = "expired"
        elif node.is_good(now):
            st = "good"
        else:
            st = "dubious"
        prev = rec.status
        if prev is not None and prev != st:
            rec.flaps += 1
            self._c_flaps.inc()
            tkey = "%s->%s" % (prev, st)
            rec.transitions[tkey] = rec.transitions.get(tkey, 0) + 1
        rec.status = st

    def _refresh_gauges(self, rec: PeerRecord) -> None:
        if rec._g_srtt is None:
            rec._g_srtt = self.reg.gauge("dht_peer_srtt_seconds",
                                         node=self.node, peer=rec.label)
            rec._g_rto = self.reg.gauge("dht_peer_rto_seconds",
                                        node=self.node, peer=rec.label)
            rec._g_fail = self.reg.gauge("dht_peer_fail_ratio",
                                         node=self.node, peer=rec.label)
        rec._g_srtt.set(-1.0 if rec.srtt is None else rec.srtt)
        rec._g_rto.set(self._rto(rec))
        fr = rec.fail_ratio()
        rec._g_fail.set(-1.0 if fr is None
                        or rec.sent < self.cfg.min_signal_events else fr)

    def _count_bytes(self, direction: str, mtype: str, n: int) -> None:
        key = (direction, mtype)
        c = self._m_bytes.get(key)
        if c is None:
            c = self._m_bytes[key] = self.reg.counter(
                "dht_peer_bytes_total", node=self.node,
                direction=direction, type=mtype)
        c.inc(n)

    # ---------------------------------------------------------------- RTO
    def _rto(self, rec: PeerRecord) -> float:
        """``max(srtt + 4*rttvar, rto_min) * 2^backoff`` clamped to
        ``rto_max``.  No-sample peers stay on exactly
        ``MAX_RESPONSE_TIME`` (the behaviour-equivalence pin) — the
        backoff only steers peers we have an estimate for, where the
        Karn sampling rule would otherwise pin a stale fast estimate
        forever (module docstring)."""
        if (not self.cfg.adaptive_rto or rec.srtt is None
                or rec.rttvar is None):
            return MAX_RESPONSE_TIME
        cfg = self.cfg
        base = max(rec.srtt + 4.0 * rec.rttvar, cfg.rto_min)
        return min(base * (1 << min(rec.backoff, 8)), cfg.rto_max)

    def rto(self, node) -> float:
        """The per-attempt retransmit timeout for this peer —
        exactly ``MAX_RESPONSE_TIME`` when disabled, the knob is off,
        or no RTT sample exists (the behaviour-equivalence pin)."""
        if not self.enabled or not self.cfg.adaptive_rto:
            return MAX_RESPONSE_TIME
        with self._lock:
            rec = self._peers.get(self._key(node))
            return MAX_RESPONSE_TIME if rec is None else self._rto(rec)

    def _sample_rtt(self, rec: PeerRecord, rtt: float) -> None:
        """RFC 6298 estimator update (first sample seeds
        rttvar = rtt/2, like TCP)."""
        if rec.srtt is None:
            rec.srtt = rtt
            rec.rttvar = rtt / 2.0
        else:
            rec.rttvar = 0.75 * rec.rttvar + 0.25 * abs(rec.srtt - rtt)
            rec.srtt = 0.875 * rec.srtt + 0.125 * rtt
        rec.samples += 1
        if rec._h_rtt is None:
            rec._h_rtt = self.reg.histogram("dht_peer_rtt_seconds",
                                            node=self.node, peer=rec.label)
        rec._h_rtt.observe(rtt)

    # ------------------------------------------------------- engine seams
    def on_send(self, node, mtype: str, nbytes: int) -> None:
        """First attempt of a request left for this peer."""
        if not self.enabled:
            return
        now = self._clock()
        with self._lock:
            rec = self._rec(node, now)
            rec.sent += 1
            rec.bytes_out[mtype] = rec.bytes_out.get(mtype, 0) + nbytes
            self._count_bytes("out", mtype, nbytes)
            self._refresh_status(rec, node, now)
            self._refresh_gauges(rec)

    def on_retransmit(self, req) -> None:
        """A real retransmission: the previous attempt timed out
        (the engine's ``_request_step`` retry site)."""
        if not self.enabled:
            return
        now = self._clock()
        mtype = req.type.value
        nbytes = len(req.msg)
        with self._lock:
            rec = self._rec(req.node, now)
            rec.attempt_timeouts += 1
            rec.backoff = min(rec.backoff + 1, 8)   # RFC 6298 §5.5
            rec.bytes_out[mtype] = rec.bytes_out.get(mtype, 0) + nbytes
            self._count_bytes("out", mtype, nbytes)
            self._refresh_status(rec, req.node, now)
            self._refresh_gauges(rec)

    def on_received(self, node, mtype: str, nbytes: int) -> None:
        """Any complete inbound message attributed to this peer
        (nbytes = 0 for reassembled multi-part values: the fragments'
        raw sizes are not retained)."""
        if not self.enabled:
            return
        now = self._clock()
        with self._lock:
            rec = self._rec(node, now)
            rec.msgs_in += 1
            if nbytes:
                rec.bytes_in[mtype] = rec.bytes_in.get(mtype, 0) + nbytes
                self._count_bytes("in", mtype, nbytes)
            self._refresh_status(rec, node, now)
            self._refresh_gauges(rec)

    def on_request_completed(self, req, rtt: Optional[float]) -> None:
        if not self.enabled:
            return
        now = self._clock()
        with self._lock:
            rec = self._rec(req.node, now)
            rec.completed += 1
            if req.attempt_count > 1:
                # the reply was already in flight when we retransmitted
                n = req.attempt_count - 1
                rec.spurious_retrans += n
                self._c_spurious.inc(n)
            elif rtt is not None:
                # Karn's rule: only un-retransmitted attempts give an
                # unambiguous RTT sample — and a clean sample ends any
                # backoff (RFC 6298 §5.7)
                rec.backoff = 0
                self._sample_rtt(rec, rtt)
            self._refresh_status(rec, req.node, now)
            self._refresh_gauges(rec)

    def on_request_expired(self, req) -> None:
        if not self.enabled:
            return
        now = self._clock()
        with self._lock:
            rec = self._rec(req.node, now)
            rec.expired += 1
            rec.backoff = min(rec.backoff + 1, 8)   # final timeout
            self._refresh_status(rec, req.node, now)
            self._refresh_gauges(rec)

    def on_request_cancelled(self, req) -> None:
        if not self.enabled:
            return
        now = self._clock()
        with self._lock:
            rec = self._rec(req.node, now)
            rec.cancelled += 1
            self._refresh_status(rec, req.node, now)
            self._refresh_gauges(rec)

    # ------------------------------------------------------------ surfaces
    def fail_signal(self) -> Optional[float]:
        """Worst per-peer fail ratio among peers with at least
        ``min_signal_events`` requests — the degrade-only ``peer_flap``
        health signal.  None (unknown, never trips) when no peer
        qualifies: a quiet or freshly booted node has no bad links."""
        if not self.enabled:
            return None
        worst = None
        with self._lock:
            for rec in self._peers.values():
                if rec.sent < self.cfg.min_signal_events:
                    continue
                fr = rec.fail_ratio()
                if fr is not None and (worst is None or fr > worst):
                    worst = fr
        return worst

    def snapshot(self) -> dict:
        """The structured document behind ``GET /peers`` / the REPL /
        the scanner; ``time`` is the ledger clock at snapshot (the
        wire-map assembler's skew check compares it against the
        scraper's wall clock, like the round-12 timeline assembler)."""
        now = self._clock()
        with self._lock:
            peers = [rec.to_doc(self._rto(rec))
                     for rec in self._peers.values()]
        peers.sort(key=lambda d: d["last_seen"], reverse=True)
        return {
            "enabled": self.enabled,
            "node": self.node,
            "time": now,
            "adaptive_rto": bool(self.cfg.adaptive_rto),
            "rto_min": self.cfg.rto_min,
            "rto_max": self.cfg.rto_max,
            "capacity": self.cfg.capacity,
            "tracked": len(peers),
            "evicted": self.evicted,
            "fail_signal": self.fail_signal(),
            "peers": peers,
        }
